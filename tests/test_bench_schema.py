"""Bench-record contract: schema validation + damaged-record recovery.

scripts/bench_schema.py guards the record bench.py emits (BENCH_OUT.json
+ final stdout line); scripts/gen_perf_tables.py must recover a record
from a driver wrapper whose ``parsed`` is null — and fail loudly when
the stdout tail was truncated mid-object (BENCH_r05's actual damage)."""

import importlib.util
import json
import pathlib

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent


def _load(name):
    path = REPO / "scripts" / f"{name}.py"
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def schema():
    return _load("bench_schema")


@pytest.fixture(scope="module")
def tables():
    return _load("gen_perf_tables")


def _rung(rate, completion=1.0, p50=50.0, p95=80.0):
    return {"offered_req_s": rate, "req_per_s": rate,
            "completion": completion, "decode_tokens_per_s": rate * 32,
            "ttft_p50_ms": p50, "ttft_p95_ms": p95}


def _serving(knee=2.0, saturated=False):
    head = ({k: None for k in ("arrival_rate_req_s", "req_per_s",
                               "decode_tokens_per_s", "ttft_p50_ms",
                               "ttft_p95_ms")}
            if saturated else
            {"arrival_rate_req_s": knee, "req_per_s": knee,
             "decode_tokens_per_s": knee * 32, "ttft_p50_ms": 50.0,
             "ttft_p95_ms": 80.0})
    return dict(head, ladder=[_rung(1.0), _rung(2.0)],
                knee_req_s=None if saturated else knee,
                saturated=saturated, burst_req_per_s=9.0,
                burst_decode_tokens_per_s=288.0, prompt_len=128,
                gen=32, slots=48, kv="int8", decode_kernel="fused")


def _record(**serving_kw):
    return {"metric": "llama_319M_train_tokens_per_sec_per_chip",
            "value": 1234.5, "unit": "tokens/sec/chip",
            "extra": {"serving": _serving(**serving_kw)}}


def _mix_block(**serving_kw):
    d = _serving(**serving_kw)
    d["batching"] = "ragged"
    d["prompt_mix"] = {"name": "short_chat", "lens": [32, 64, 128],
                       "weights": [0.5, 0.3, 0.2], "sampled_p50": 32,
                       "sampled_p95": 128, "sampled_max": 128}
    return d


def _mixed_record(**serving_kw):
    rec = _record()
    rec["extra"]["serving_mixed"] = {
        "batching": "ragged",
        "mixes": {"short_chat": _mix_block(**serving_kw),
                  "long_rag": _mix_block(saturated=True)}}
    return rec


def test_valid_record_is_clean(schema):
    assert schema.validate_record(_record()) == []


def test_valid_saturated_record_is_clean(schema):
    assert schema.validate_record(_record(saturated=True)) == []


def test_missing_top_level_keys(schema):
    rec = _record()
    del rec["metric"]
    rec["value"] = "fast"
    probs = schema.validate_record(rec)
    assert any("metric" in p for p in probs)
    assert any("value" in p for p in probs)


def test_knee_and_saturated_are_exclusive(schema):
    rec = _record()
    rec["extra"]["serving"]["saturated"] = True  # but knee_req_s set
    probs = schema.validate_record(rec)
    assert any("not both" in p for p in probs)

    rec = _record(saturated=True)
    rec["extra"]["serving"]["saturated"] = False  # but knee is null
    probs = schema.validate_record(rec)
    assert any("must name its knee" in p for p in probs)


def test_saturated_record_may_not_carry_headline_numbers(schema):
    rec = _record(saturated=True)
    rec["extra"]["serving"]["ttft_p50_ms"] = 247.1
    probs = schema.validate_record(rec)
    assert any("headline" in p for p in probs)


def test_ladder_rungs_must_be_numeric(schema):
    rec = _record()
    rec["extra"]["serving"]["ladder"][1]["completion"] = None
    probs = schema.validate_record(rec)
    assert any("ladder[1].completion" in p for p in probs)


def test_error_leg_is_valid(schema):
    rec = _record()
    rec["extra"]["serving_1b"] = {"error": "RESOURCE_EXHAUSTED"}
    assert schema.validate_record(rec) == []


# --- mixed-length ladder blocks --------------------------------------------


def test_valid_mixed_record_is_clean(schema):
    assert schema.validate_record(_mixed_record()) == []


def test_mixed_knee_saturated_exclusivity_applies_per_mix(schema):
    rec = _mixed_record()
    mix = rec["extra"]["serving_mixed"]["mixes"]["long_rag"]
    mix["knee_req_s"] = 2.0  # but the mix says saturated
    probs = schema.validate_record(rec)
    assert any("mixes[long_rag]" in p and "not both" in p for p in probs)


def test_mix_without_prompt_distribution_is_flagged(schema):
    rec = _mixed_record()
    del rec["extra"]["serving_mixed"]["mixes"]["short_chat"]["prompt_mix"]
    probs = schema.validate_record(rec)
    assert any("missing prompt_mix" in p for p in probs)


def test_prompt_mix_weights_must_sum_to_one_over_lens(schema):
    rec = _mixed_record()
    pm = rec["extra"]["serving_mixed"]["mixes"]["short_chat"]["prompt_mix"]
    pm["weights"] = [0.5, 0.3]  # length mismatch
    probs = schema.validate_record(rec)
    assert any("3 lens but 2 weights" in p for p in probs)
    pm["weights"] = [0.5, 0.3, 0.1]  # sums to 0.9
    probs = schema.validate_record(rec)
    assert any("sum to 0.9" in p for p in probs)
    pm["weights"] = [0.5, 0.3, "lots"]
    probs = schema.validate_record(rec)
    assert any("non-negative numbers" in p for p in probs)


def test_mixed_block_requires_batching_and_mixes(schema):
    rec = _mixed_record()
    rec["extra"]["serving_mixed"]["batching"] = "eager"
    rec["extra"]["serving_mixed"]["mixes"] = {}
    probs = schema.validate_record(rec)
    assert any("batching" in p for p in probs)
    assert any("non-empty object" in p for p in probs)


def _prefix_block():
    return {"requests": 96, "hit_ratio": 0.61, "hit_token_ratio": 0.45,
            "cold_requests": 30, "hit50_requests": 40,
            "ttft_mean_cold_ms": 82.0, "ttft_mean_hit50_ms": 31.0,
            "ttft_p50_cold_ms": 78.0, "ttft_p50_hit50_ms": 29.0,
            "cached_pages": 120, "evicted_pages": 14}


def test_prefix_block_valid(schema):
    rec = _mixed_record()
    mixes = rec["extra"]["serving_mixed"]["mixes"]
    mixes["zipf_chat"] = _mix_block()
    mixes["zipf_chat"]["prefix"] = _prefix_block()
    assert schema.validate_record(rec) == []


def test_prefix_block_ratio_bounds_and_required_keys(schema):
    rec = _mixed_record()
    mixes = rec["extra"]["serving_mixed"]["mixes"]
    mixes["zipf_chat"] = _mix_block()
    px = _prefix_block()
    px["hit_ratio"] = 1.4
    del px["cached_pages"]
    mixes["zipf_chat"]["prefix"] = px
    probs = schema.validate_record(rec)
    assert any("hit_ratio=1.4" in p and "outside [0, 1]" in p
               for p in probs)
    assert any("prefix.cached_pages" in p for p in probs)


def test_prefix_block_ttft_null_only_when_class_empty(schema):
    """A cold TTFT may be null ONLY when there were no cold requests —
    otherwise a run could fake an unbeatable cache by dropping its
    baseline."""
    rec = _mixed_record()
    mixes = rec["extra"]["serving_mixed"]["mixes"]
    mixes["zipf_chat"] = _mix_block()
    px = _prefix_block()
    px["ttft_mean_cold_ms"] = None  # but cold_requests = 30
    mixes["zipf_chat"]["prefix"] = px
    probs = schema.validate_record(rec)
    assert any("null" in p and "ttft_mean_cold_ms" in p for p in probs)
    px["cold_requests"] = 0  # empty class: null is now honest
    assert schema.validate_record(rec) == []
    px["ttft_mean_hit50_ms"] = "fast"
    probs = schema.validate_record(rec)
    assert any("ttft_mean_hit50_ms" in p and "neither" in p
               for p in probs)


def test_mixed_error_leg_is_valid(schema):
    rec = _record()
    rec["extra"]["serving_1b_mixed"] = {"error": "RESOURCE_EXHAUSTED"}
    assert schema.validate_record(rec) == []
    rec["extra"]["serving_1b_mixed"] = {
        "batching": "ragged",
        "mixes": {"bursty": {"error": "RESOURCE_EXHAUSTED"}}}
    assert schema.validate_record(rec) == []


# --- speculative-decoding blocks -------------------------------------------


def _spec_block():
    return {"rounds": 40, "drafted_tokens": 160, "accepted_tokens": 150,
            "accept_ratio": 0.938, "accepted_tokens_per_step": 4.75,
            "cooldowns": 0, "k": 4, "draft": "self"}


def _spec_ablation_block():
    return {"on": {"decode_tokens_per_s": 520.0, "accept_ratio": 0.94,
                   "accepted_tokens_per_step": 4.75},
            "off": {"decode_tokens_per_s": 310.0},
            "speedup": 1.68}


def test_spec_blocks_valid(schema):
    rec = _mixed_record()
    mix = rec["extra"]["serving_mixed"]["mixes"]["short_chat"]
    mix["spec"] = _spec_block()
    mix["spec_ablation"] = _spec_ablation_block()
    assert schema.validate_record(rec) == []
    # A standalone serving leg may carry spec without the ablation.
    rec2 = _record()
    rec2["extra"]["serving"]["spec"] = _spec_block()
    assert schema.validate_record(rec2) == []
    # An honest probe error passes through.
    mix["spec_ablation"] = {"error": "RESOURCE_EXHAUSTED"}
    assert schema.validate_record(rec) == []


def test_spec_block_absent_not_zero(schema):
    """A leg that never completed a verify round must omit the spec
    block entirely — rounds=0 inside one is flagged."""
    rec = _record()
    sp = _spec_block()
    sp["rounds"] = 0
    rec["extra"]["serving"]["spec"] = sp
    probs = schema.validate_record(rec)
    assert any("absent, not zero" in p for p in probs)


def test_spec_ratio_bounds_and_accept_le_drafted(schema):
    rec = _record()
    sp = _spec_block()
    sp["accept_ratio"] = 1.4
    sp["accepted_tokens"] = 200  # > drafted 160
    rec["extra"]["serving"]["spec"] = sp
    probs = schema.validate_record(rec)
    assert any("accept_ratio=1.4" in p and "[0, 1]" in p for p in probs)
    assert any("accepts a prefix of its draft" in p for p in probs)
    sp = _spec_block()
    sp["accept_ratio"] = None  # but drafted_tokens = 160
    rec["extra"]["serving"]["spec"] = sp
    probs = schema.validate_record(rec)
    assert any("null is only honest" in p for p in probs)


def test_spec_tokens_per_step_must_be_positive(schema):
    rec = _record()
    sp = _spec_block()
    sp["accepted_tokens_per_step"] = 0
    rec["extra"]["serving"]["spec"] = sp
    probs = schema.validate_record(rec)
    assert any("accepted_tokens_per_step" in p and "bonus token" in p
               for p in probs)


def test_spec_ablation_iff_spec_ran(schema):
    """A speculative MIX leg must carry its on/off A/B, and no leg may
    carry an ablation without a spec block."""
    rec = _mixed_record()
    mix = rec["extra"]["serving_mixed"]["mixes"]["short_chat"]
    mix["spec"] = _spec_block()  # no spec_ablation
    probs = schema.validate_record(rec)
    assert any("must carry its on/off A/B" in p for p in probs)
    del mix["spec"]
    mix["spec_ablation"] = _spec_ablation_block()
    probs = schema.validate_record(rec)
    assert any("a leg that never speculated" in p for p in probs)


def test_spec_ablation_leg_shapes(schema):
    rec = _mixed_record()
    mix = rec["extra"]["serving_mixed"]["mixes"]["short_chat"]
    mix["spec"] = _spec_block()
    ab = _spec_ablation_block()
    ab["off"]["accept_ratio"] = 0.9  # off leg has no acceptance
    del ab["on"]["decode_tokens_per_s"]
    mix["spec_ablation"] = ab
    probs = schema.validate_record(rec)
    assert any("spec-off leg has no acceptance" in p for p in probs)
    assert any("on.decode_tokens_per_s" in p for p in probs)


def _multihost_rung(shards=2, tp=2, mode="int8", dcn=1152,
                    ratio=3.55):
    return {"shards": shards, "tp": tp, "dcn_collective": mode,
            "toks_per_s": 120.0, "ici_bytes_per_step": 4096,
            "dcn_bytes_per_step": dcn,
            "dcn_bytes_ratio_vs_fp32": ratio}


def _multihost_block():
    return {"ladder": [
        _multihost_rung(shards=1, tp=4, mode="bf16", dcn=0, ratio=None),
        _multihost_rung(mode="bf16", dcn=4096, ratio=1.0),
        _multihost_rung(mode="int8"),
    ]}


def test_multihost_block_valid(schema):
    rec = _record()
    rec["extra"]["serving_multihost"] = _multihost_block()
    assert schema.validate_record(rec) == []
    rec["extra"]["serving_multihost"] = {"error": "RESOURCE_EXHAUSTED"}
    assert schema.validate_record(rec) == []


def test_multihost_rung_required_keys_and_bounds(schema):
    rec = _record()
    mh = _multihost_block()
    del mh["ladder"][2]["dcn_bytes_per_step"]
    mh["ladder"][1]["toks_per_s"] = 0
    rec["extra"]["serving_multihost"] = mh
    probs = schema.validate_record(rec)
    assert any("dcn_bytes_per_step" in p for p in probs)
    assert any("toks_per_s" in p for p in probs)


def test_multihost_int8_rung_must_show_3x(schema):
    """The quantization claim is load-bearing: an int8 rung whose
    recorded ratio is under 3x (or missing) fails validation."""
    rec = _record()
    mh = _multihost_block()
    mh["ladder"][2]["dcn_bytes_ratio_vs_fp32"] = 2.3
    rec["extra"]["serving_multihost"] = mh
    assert any(">= 3x" in p for p in schema.validate_record(rec))
    mh["ladder"][2]["dcn_bytes_ratio_vs_fp32"] = None
    assert any(">= 3x" in p for p in schema.validate_record(rec))


def test_multihost_multi_shard_rungs_need_ablation(schema):
    """Multi-shard rungs with only one DCN mode recorded — the
    quantized-vs-exact ablation never ran — are flagged."""
    rec = _record()
    mh = _multihost_block()
    mh["ladder"] = [r for r in mh["ladder"]
                    if r["dcn_collective"] == "int8" or r["shards"] == 1]
    rec["extra"]["serving_multihost"] = mh
    assert any("ablation" in p for p in schema.validate_record(rec))


def test_multihost_multi_shard_rung_puts_bytes_on_dcn(schema):
    rec = _record()
    mh = _multihost_block()
    mh["ladder"][2]["dcn_bytes_per_step"] = 0
    rec["extra"]["serving_multihost"] = mh
    probs = schema.validate_record(rec)
    assert any("puts bytes on the DCN" in p for p in probs)


# --- disaggregated prefill/decode ablation ---------------------------------


def _disagg_leg():
    return {"ttft_p50_ms": 120.0, "ttft_p95_ms": 310.0,
            "itl_p50_ms": 18.0, "itl_p95_ms": 42.0,
            "decode_tokens_per_s": 410.0}


def _disagg_block():
    dis = dict(_disagg_leg(), handoff_gap_p50_ms=12.0,
               migration={"pages": 84, "wire_bytes": 1376256,
                          "seconds": 0.41, "failed": 0})
    return {"mix": {"name": "long_rag", "lens": [512, 1024, 1536],
                    "weights": [0.3, 0.5, 0.2]},
            "n_requests": 10, "gen": 24, "handoff_after_tokens": 2,
            "transfer": "int8", "unified": _disagg_leg(),
            "disagg": dis, "itl_p95_ratio": 1.35}


def test_disagg_block_valid(schema):
    rec = _record()
    rec["extra"]["serving_disagg"] = _disagg_block()
    assert schema.validate_record(rec) == []
    rec["extra"]["serving_disagg"] = {"error": "RESOURCE_EXHAUSTED"}
    assert schema.validate_record(rec) == []


def test_disagg_required_keys_and_legs(schema):
    rec = _record()
    blk = _disagg_block()
    del blk["unified"]["itl_p95_ms"]
    blk["transfer"] = "bf16"
    rec["extra"]["serving_disagg"] = blk
    probs = schema.validate_record(rec)
    assert any("unified.itl_p95_ms" in p for p in probs)
    assert any("transfer must be 'int8' or 'exact'" in p for p in probs)


def test_disagg_leg_must_move_pages(schema):
    """A 'disagg' ablation whose migration block shows no pages on the
    wire never disaggregated anything — flagged, as are pages without
    bytes and a missing migration block entirely."""
    rec = _record()
    blk = _disagg_block()
    rec["extra"]["serving_disagg"] = blk
    blk["disagg"]["migration"]["pages"] = 0
    probs = schema.validate_record(rec)
    assert any("measured unified serving twice" in p for p in probs)
    blk["disagg"]["migration"] = {"pages": 5, "wire_bytes": 0,
                                  "seconds": 0.1, "failed": 0}
    probs = schema.validate_record(rec)
    assert any("no bytes on the wire" in p for p in probs)
    del blk["disagg"]["migration"]
    probs = schema.validate_record(rec)
    assert any("missing migration block" in p for p in probs)


def test_disagg_mix_distribution_checked(schema):
    rec = _record()
    blk = _disagg_block()
    blk["mix"]["weights"] = [0.3, 0.5]
    rec["extra"]["serving_disagg"] = blk
    assert any("3 lens but 2 weights" in p
               for p in schema.validate_record(rec))
    blk["mix"]["weights"] = [0.3, 0.5, 0.1]
    assert any("sum to 0.9" in p for p in schema.validate_record(rec))


# --- LoRA multiplexing ablation --------------------------------------------


def _adapters_block():
    return {"mix": {"name": "zipf_adapters", "n_adapters": 6,
                    "zipf_alpha": 1.1, "pool_adapters": 4, "rank": 4},
            "n_requests": 24, "gen": 16,
            "single_model": {"tokens_per_s": 420.0,
                             "ttft_p50_ms": 35.0, "ttft_p95_ms": 80.0},
            "multi": {"tokens_per_s": 365.0, "ttft_p50_ms": 41.0,
                      "ttft_p95_ms": 96.0,
                      "pool": {"pool_pages": 8, "resident": 4,
                               "hits": 17, "misses": 7,
                               "evictions": 3, "hit_ratio": 0.708}},
            "throughput_degradation": 0.869}


def test_adapters_block_valid(schema):
    rec = _record()
    rec["extra"]["serving_adapters"] = _adapters_block()
    assert schema.validate_record(rec) == []
    rec["extra"]["serving_adapters"] = {"error": "RESOURCE_EXHAUSTED"}
    assert schema.validate_record(rec) == []


def test_adapters_hit_ratio_is_a_fraction(schema):
    rec = _record()
    blk = _adapters_block()
    rec["extra"]["serving_adapters"] = blk
    blk["multi"]["pool"]["hit_ratio"] = 1.7
    probs = schema.validate_record(rec)
    assert any("hit_ratio" in p and "[0, 1]" in p for p in probs)
    del blk["multi"]["pool"]
    probs = schema.validate_record(rec)
    assert any("missing pool block" in p for p in probs)


def test_adapters_degradation_iff_both_legs_ran(schema):
    """throughput_degradation must exist when both legs ran and must
    NOT exist when one didn't — a ratio over a missing leg is
    fabricated."""
    rec = _record()
    blk = _adapters_block()
    rec["extra"]["serving_adapters"] = blk
    blk["throughput_degradation"] = None
    probs = schema.validate_record(rec)
    assert any("never priced the multiplexing" in p for p in probs)
    blk = _adapters_block()
    del blk["single_model"]
    rec["extra"]["serving_adapters"] = blk
    probs = schema.validate_record(rec)
    assert any("a ratio over a leg that never ran" in p for p in probs)


def test_prefix_migration_cost_field(schema):
    """The migrated-vs-recomputed field in the zipf_chat prefix block:
    valid when complete, per-page cost null only when nothing moved,
    and an honest probe error passes through."""
    rec = _mixed_record()
    mixes = rec["extra"]["serving_mixed"]["mixes"]
    mixes["zipf_chat"] = _mix_block()
    px = _prefix_block()
    px["migration"] = {"migrated_pages": 120, "wire_bytes": 1966080,
                       "seconds": 0.8, "migrate_s_per_page": 0.0067,
                       "recompute_s_per_page": 0.021,
                       "migrate_vs_recompute": 3.13}
    mixes["zipf_chat"]["prefix"] = px
    assert schema.validate_record(rec) == []
    px["migration"]["migrate_s_per_page"] = None  # but pages moved
    probs = schema.validate_record(rec)
    assert any("null migrate_s_per_page" in p for p in probs)
    px["migration"] = {"migrated_pages": 120, "wire_bytes": 0,
                       "seconds": 0.8, "migrate_s_per_page": 0.0067}
    probs = schema.validate_record(rec)
    assert any("no bytes on the wire" in p for p in probs)
    px["migration"] = {"error": "cold engine OOM"}
    assert schema.validate_record(rec) == []


# --- autoscaling chaos leg --------------------------------------------------


def _chaos_block():
    return {"mix": "zipf_chat", "offered": 24, "completed": 21,
            "shed": 3, "failed": 0, "shed_fraction": 0.125,
            "goodput_ratio": 1.0, "scale_ups": 1, "scale_downs": 1,
            "drain_retirements": 2, "kills": 1,
            "controller_kills": 1, "recovery_seconds": 1.42,
            "max_groups": 3, "max_replicas": 3, "gen": 10,
            "doctor": {"checks_run": 14, "violations": 0,
                       "audit_seconds": 0.02}}


def test_chaos_block_valid(schema):
    rec = _record()
    rec["extra"]["serving_chaos"] = _chaos_block()
    assert schema.validate_record(rec) == []
    rec["extra"]["serving_chaos"] = {"error": "RESOURCE_EXHAUSTED"}
    assert schema.validate_record(rec) == []


def test_chaos_required_keys_and_fractions(schema):
    rec = _record()
    blk = _chaos_block()
    del blk["kills"]
    blk["goodput_ratio"] = 1.3
    rec["extra"]["serving_chaos"] = blk
    probs = schema.validate_record(rec)
    assert any("missing required key 'kills'" in p for p in probs)
    assert any("goodput_ratio=1.3" in p and "[0, 1]" in p for p in probs)


def test_chaos_leg_must_exercise_the_policy(schema):
    """A chaos record showing no scale-up, no scale-down, or no kill
    measured a static fleet on a sunny day — each is flagged."""
    rec = _record()
    blk = _chaos_block()
    blk["scale_ups"] = 0
    blk["scale_downs"] = 0
    blk["kills"] = 0
    rec["extra"]["serving_chaos"] = blk
    probs = schema.validate_record(rec)
    assert any("scale_ups=0" in p and "static fleet" in p for p in probs)
    assert any("scale_downs=0" in p and "drain" in p for p in probs)
    assert any("kills=0" in p for p in probs)


def test_chaos_doctor_requires_clean_audit(schema):
    """The post-ramp doctor audit gates the record: any violation, or
    a pass that ran zero checks, flags the leg no matter how healthy
    its goodput looks.  A legacy block without a doctor key stays
    valid (old records predate the audit plane)."""
    rec = _record()
    blk = _chaos_block()
    blk["doctor"] = {"checks_run": 0, "violations": 2,
                     "audit_seconds": -1.0}
    rec["extra"]["serving_chaos"] = blk
    probs = schema.validate_record(rec)
    assert any("checks_run=0" in p and "audited" in p for p in probs)
    assert any("violations=2" in p and "corrupted" in p for p in probs)
    assert any("audit_seconds=-1.0" in p for p in probs)
    blk["doctor"] = "clean"
    assert any("not an object" in p
               for p in schema.validate_record(rec))
    del blk["doctor"]
    assert schema.validate_record(rec) == []


def test_chaos_sheds_are_not_completions(schema):
    """completed + shed must not exceed offered: a leg double-counting
    shed requests as completions is cooking its goodput."""
    rec = _record()
    blk = _chaos_block()
    blk["completed"] = 23  # 23 + 3 > 24 offered
    rec["extra"]["serving_chaos"] = blk
    probs = schema.validate_record(rec)
    assert any("exceeds offered=24" in p for p in probs)


def test_chaos_scale_up_reasons_breakdown(schema):
    """ISSUE 18 satellite: the scale_up_reasons breakdown uses known
    reasons only, counts >= 1 (absent-not-zero — a reason that never
    fired is omitted, never reported as 0), and sums to scale_ups."""
    rec = _record()
    blk = _chaos_block()
    blk["scale_ups"] = 3
    blk["scale_up_reasons"] = {"arrival_slope": 1, "queue_age": 2}
    rec["extra"]["serving_chaos"] = blk
    assert schema.validate_record(rec) == []

    blk["scale_up_reasons"] = {"vibes": 3}
    probs = schema.validate_record(rec)
    assert any("unknown reason 'vibes'" in p for p in probs)

    blk["scale_up_reasons"] = {"arrival_slope": 0, "queue_age": 3}
    probs = schema.validate_record(rec)
    assert any("arrival_slope=0" in p and "omitted, not zero" in p
               for p in probs)

    blk["scale_up_reasons"] = {"queue_age": 1}  # sums to 1, not 3
    probs = schema.validate_record(rec)
    assert any("breakdown sums to 1" in p and "scale_ups=3" in p
               for p in probs)

    # Field absent entirely: valid (older records never measured it).
    del blk["scale_up_reasons"]
    assert schema.validate_record(rec) == []


def test_chaos_controller_kill_requires_measured_recovery(schema):
    """ISSUE 20 satellite: the control-plane chaos arm.  A record
    claiming controller_kills >= 1 must carry a numeric
    recovery_seconds >= 0 (the kill was observed recovering);
    legacy records without either key stay valid, and a kill-free
    record may honestly report recovery_seconds as null."""
    rec = _record()
    blk = _chaos_block()
    rec["extra"]["serving_chaos"] = blk
    assert schema.validate_record(rec) == []

    # Killed the controller but never measured the recovery: invalid.
    blk["recovery_seconds"] = None
    probs = schema.validate_record(rec)
    assert any("controller_kills=1" in p
               and "recovery_seconds=None" in p for p in probs)
    blk["recovery_seconds"] = "fast"
    probs = schema.validate_record(rec)
    assert any("recovery_seconds='fast'" in p for p in probs)

    # No controller kill this run: null recovery is honest.
    blk["controller_kills"] = 0
    blk["recovery_seconds"] = None
    assert schema.validate_record(rec) == []
    blk["recovery_seconds"] = "fast"  # but a non-number still isn't
    probs = schema.validate_record(rec)
    assert any("neither a number nor null" in p for p in probs)

    blk["controller_kills"] = -1
    blk["recovery_seconds"] = None
    probs = schema.validate_record(rec)
    assert any("controller_kills=-1" in p for p in probs)

    # Pre-FT record: both keys absent entirely — valid.
    del blk["controller_kills"]
    del blk["recovery_seconds"]
    assert schema.validate_record(rec) == []


def test_bench_out_if_present(schema):
    """Whatever BENCH_OUT.json the last bench run left behind must
    satisfy the schema (skips when no run has happened here)."""
    path = REPO / "BENCH_OUT.json"
    if not path.exists():
        pytest.skip("no BENCH_OUT.json in the repo")
    rec = json.loads(path.read_text())
    assert schema.validate_record(rec) == []


def test_bench_main_emits_file_and_stdout_line(schema, tmp_path,
                                               monkeypatch, capsys):
    """bench.main() end-to-end (measurement stubbed): the record lands
    in BENCH_OUT.json AND as the final stdout line, the two copies are
    byte-identical, the line is COMPACT (the driver wrapper keeps only
    a bounded stdout tail — padding is what truncated BENCH_r05's line
    into parsed:null), and the record satisfies the schema."""
    spec = importlib.util.spec_from_file_location("bench",
                                                  REPO / "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    monkeypatch.setattr(bench, "_measure", lambda *a, **k: 1000.0)
    monkeypatch.setattr(bench, "_measure_serving_multihost",
                        lambda *a, **k: _multihost_block())
    monkeypatch.setattr(bench, "_measure_serving_disagg",
                        lambda *a, **k: _disagg_block())
    monkeypatch.setattr(bench, "_measure_serving_chaos",
                        lambda *a, **k: _chaos_block())
    monkeypatch.chdir(tmp_path)
    bench.main()
    lines = capsys.readouterr().out.strip().splitlines()
    file_text = (tmp_path / "BENCH_OUT.json").read_text().strip()
    assert lines[-1] == file_text
    assert ": " not in lines[-1] and ", " not in lines[-1]
    rec = json.loads(lines[-1])
    assert schema.validate_record(rec) == []


# --- the measured full-8B ZeRO train rung ----------------------------------


def _zero_train(shards=4):
    return {"params_b": 8.03, "measured": True,
            "tokens_per_sec_per_chip": 520.0, "mfu": 0.31,
            "zero_sharding": True, "dp_shards": shards, "grad_accum": 4,
            "batch": 4 * shards, "seq": 2048,
            "optimizer": "adamw8bit (int8 states, ZeRO-sharded)",
            "opt_state_bytes_per_param": 2.03 / shards,
            "opt_state_bytes_per_device": 4_075_000_000 // shards,
            "hbm_peak_gb": 11.2}


def _rec_8b(train):
    rec = _record()
    rec["extra"]["llama_8b"] = {"params_b": 8.03, "train": train}
    return rec


def test_zero_train_rung_valid(schema):
    assert schema.validate_record(_rec_8b(_zero_train())) == []


def test_zero_train_error_rung_valid(schema):
    err = {"error": "full-8B AdamW needs ~51.7 GiB/chip on 1 chip(s)",
           "zero_sharding": True, "dp_shards": 1, "min_chips": 4}
    assert schema.validate_record(_rec_8b(err)) == []
    rec = _record()
    rec["extra"]["llama_8b"] = {"error": "RESOURCE_EXHAUSTED"}
    assert schema.validate_record(rec) == []


def test_extrapolated_8b_train_is_retired(schema):
    """A lingering train_extrapolated block — the pre-ZeRO path that
    modeled 32 layers from a 4-layer run — fails validation outright."""
    rec = _rec_8b(_zero_train())
    rec["extra"]["llama_8b"]["train_extrapolated"] = {
        "extrapolated_mfu": 0.45}
    probs = schema.validate_record(rec)
    assert any("train_extrapolated" in p and "retired" in p
               for p in probs)


def test_llama_8b_without_train_rung_is_flagged(schema):
    rec = _record()
    rec["extra"]["llama_8b"] = {"params_b": 8.03}
    probs = schema.validate_record(rec)
    assert any("missing the measured 'train' rung" in p for p in probs)


def test_zero_train_must_be_measured_and_sharded(schema):
    tr = _zero_train()
    tr["measured"] = False
    probs = schema.validate_record(_rec_8b(tr))
    assert any("measured=False" in p and "retired" in p for p in probs)
    tr = _zero_train()
    tr["zero_sharding"] = False
    probs = schema.validate_record(_rec_8b(tr))
    assert any("zero_sharding=False" in p for p in probs)


def test_zero_train_memory_claim_is_checked(schema):
    """opt_state_bytes_per_param must shrink with dp_shards: a rung
    claiming 4-way sharding while reporting ~2 B/param kept its state
    replicated and fails."""
    tr = _zero_train(shards=4)
    tr["opt_state_bytes_per_param"] = 2.03  # replicated footprint
    probs = schema.validate_record(_rec_8b(tr))
    assert any("exceeds" in p and "2.5/dp_shards" in p for p in probs)
    tr["opt_state_bytes_per_param"] = 0.6  # <= 2.5/4
    assert schema.validate_record(_rec_8b(tr)) == []


def test_zero_train_mfu_bounds(schema):
    tr = _zero_train()
    tr["mfu"] = 1.7
    probs = schema.validate_record(_rec_8b(tr))
    assert any("mfu=1.7" in p for p in probs)
    tr["mfu"] = None
    probs = schema.validate_record(_rec_8b(tr))
    assert any("mfu=None" in p for p in probs)


def test_tables_refuse_extrapolated_8b_record(tables):
    rec = _record()
    rec["extra"]["llama_8b"] = {
        "train_extrapolated": {"extrapolated_mfu": 0.45}}
    with pytest.raises(SystemExit, match="retired"):
        tables.render(rec)


def test_tables_refuse_8b_record_without_train_rung(tables):
    rec = _record()
    rec["extra"]["llama_8b"] = {"params_b": 8.03}
    with pytest.raises(SystemExit, match="no measured 'train' rung"):
        tables.render(rec)


def test_tables_render_measured_8b_train_row(tables):
    block = tables.render(_rec_8b(_zero_train()))
    row = next(l for l in block.splitlines()
               if "Llama-3-8B" in l and "MEASURED" in l)
    assert "ZeRO-sharded 4x" in row
    assert "0.5k" in row and "0.31" in row


def test_tables_render_infeasible_8b_train_row(tables):
    """An honest infeasibility record (too few chips even sharded)
    renders an empty row that says why, instead of vanishing."""
    block = tables.render(_rec_8b(
        {"error": "needs ~51.7 GiB/chip", "zero_sharding": True}))
    row = next(l for l in block.splitlines() if "Llama-3-8B" in l)
    assert "infeasible" in row and "| — | — |" in row


# --- gen_perf_tables damaged-record recovery -------------------------------


def test_recover_last_json_line(tables):
    rec = _record()
    wrapper = {"n": 6, "cmd": "python bench.py", "rc": 0, "parsed": None,
               "tail": ("some warning line\n"
                        '{"not": "the record"}\n'
                        + json.dumps(rec) + "\n")}
    got = tables.recover_record(wrapper)
    assert got == rec


def test_recovery_fails_loudly_on_truncated_tail(tables):
    """BENCH_r05's damage: the tail starts mid-object, so no complete
    JSON line survives — the script must die loudly, not guess."""
    wrapper = {"parsed": None,
               "tail": '_s": 21.64, "completion": 0.985}}}'}
    with pytest.raises(SystemExit, match="no complete bench JSON"):
        tables.recover_record(wrapper)


def test_recovery_fails_loudly_on_real_r05_wrapper(tables):
    wrapper = json.loads((REPO / "BENCH_r05.json").read_text())
    assert wrapper["parsed"] is None
    with pytest.raises(SystemExit):
        tables.recover_record(wrapper, "BENCH_r05.json")


def test_render_saturated_ladder_never_shows_a_knee(tables):
    """Old records (no ``saturated`` key) with a collapsed ladder must
    render as saturated, not present the lowest rung as the knee —
    the exact mislabeling BENCH_r05's 1.14B row shipped with."""
    legacy = {"burst_req_per_s": 5.0, "burst_decode_tokens_per_s": 160.0,
              "slots": 32, "kv": "bf16", "knee_req_s": 3.0,
              "arrival_rate_req_s": 3.0, "ttft_p50_ms": 247.1,
              "ttft_p95_ms": 50156.4,
              "ladder": [_rung(3.0, completion=0.116, p50=247.1,
                               p95=50156.4)]}
    rec = {"metric": "m", "value": 1.0, "unit": "u",
           "extra": {"serving_1b": legacy}}
    block = tables.render(rec)
    row = next(l for l in block.splitlines() if "1.14B" in l)
    assert "saturated" in row
    assert "3.0" not in row and "247.1" not in row


def test_render_spec_ablation_table(tables):
    """A mixed record with a speculative mix renders the spec table;
    a record with no spec block anywhere omits it entirely."""
    rec = _mixed_record()
    assert "Speculative decoding" not in tables.render(rec)
    mix = rec["extra"]["serving_mixed"]["mixes"]["short_chat"]
    mix["spec"] = _spec_block()
    mix["spec_ablation"] = _spec_ablation_block()
    block = tables.render(rec)
    assert "Speculative decoding" in block
    row = next(l for l in block.splitlines()
               if l.startswith("| short_chat"))
    assert "0.938" in row and "4.75" in row
    assert "520.0" in row and "310.0" in row and "1.68" in row


def test_render_fused_kernel_row_labeled(tables):
    rec = _record()
    block = tables.render(rec)
    row = next(l for l in block.splitlines()
               if "319M" in l and "slots" in l)
    assert "fused decode" in row
    assert "2.0" in row  # the knee rate
