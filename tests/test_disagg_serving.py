"""Disaggregated prefill/decode serving + the KV page-migration plane.

Correctness contract: disaggregation is an OPTIMIZATION, never a
semantics change — greedy (temperature=0) streams served across a
prefill→decode handoff are byte-identical to the unified
single-replica oracle, and every failure mode (no decode target, dead
prefill replica, aborted transfer) degrades to the PR-5 continuation
replay — local recompute, never a stall and never a different token.

Accounting contract: pages pinned under a migration lease are
eviction-proof but stay owned by the prefix index, so the pool
invariant extends to free ∪ cached ∪ slot-owned with
borrowed ⊆ cached and leased ⊆ cached — across finish, cancel
mid-migration, and lease release.

Prefix migration: a cold engine ingests a warm engine's exported hot
prefixes and then admits a matching prompt entirely from the migrated
pages (prefix_hit == transferred pages), with no recompute of the
migrated tokens.
"""

import re
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import llama
from ray_tpu.serve import kv_transfer
from ray_tpu.serve.config import DeploymentConfig, DisaggConfig
from ray_tpu.serve.kv_transfer import DisaggContext, set_disagg
from ray_tpu.serve.llm_engine import (
    EngineConfig,
    LLMEngine,
    LLMServer,
    llama_paged_adapter,
)

CFG = llama.LlamaConfig(
    vocab_size=128, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
    mlp_dim=64, max_seq_len=128, remat=False, dtype=jnp.float32,
    param_dtype=jnp.float32,
)

PAGE = 4


@pytest.fixture(scope="module")
def params():
    return llama.init_params(jax.random.key(0), CFG)


def greedy_reference(params, prompt, n_tokens):
    toks = list(prompt)
    out = []
    for _ in range(n_tokens):
        logits = llama.forward(params, jnp.asarray([toks]), CFG)
        nxt = int(jnp.argmax(logits[0, -1]))
        out.append(nxt)
        toks.append(nxt)
    return out


def _engine(params, **kw):
    cfg = dict(max_slots=4, max_seq_len=64, min_prefill_bucket=16,
               page_size=PAGE, ragged_batching=True, token_budget=64,
               prefix_cache=True)
    cfg.update(kw)
    return LLMEngine(params, llama_paged_adapter(CFG), EngineConfig(**cfg))


def _assert_pool_consistent(eng):
    """test_prefix_cache's invariant, extended with the migration
    lease: every physical page in exactly one of free / cached /
    slot-owned, borrowed ⊆ cached, AND leased ⊆ cached (a lease pins,
    it does not own)."""
    free = list(eng._free_pages)
    assert len(free) == len(set(free)), "duplicate pages on free list"
    free = set(free)
    cached = eng._prefix.pages()
    owned = set()
    for slot, pages in eng._slot_pages.items():
        b = eng._slot_borrowed.get(slot, [])
        tail = pages[len(b):]
        assert not owned & set(tail), "page owned by two slots"
        owned |= set(tail)
    leased = eng._prefix.leased_pages()
    assert leased <= cached, "leased page not owned by the index"
    assert not free & cached and not free & owned
    assert not cached & owned
    assert len(free) + len(cached) + len(owned) == eng._num_pages, (
        f"pool leak: {len(free)} free + {len(cached)} cached + "
        f"{len(owned)} owned != {eng._num_pages}")


def _metric_total(pattern: str) -> float:
    """Sum of samples whose exposition line matches ``pattern``
    (regex over family + label block)."""
    from ray_tpu.util import metrics

    total = 0.0
    pat = re.compile(rf"^{pattern}[^ ]* (\S+)$")
    for line in metrics.export_prometheus().splitlines():
        m = pat.match(line)
        if m:
            total += float(m.group(1))
    return total


# -- config + role validation ------------------------------------------------

def test_disagg_config_validation(params):
    with pytest.raises(ValueError, match="prefill_replicas"):
        DisaggConfig(prefill_replicas=0)
    with pytest.raises(ValueError, match="transfer"):
        DisaggConfig(transfer="fp4")
    with pytest.raises(ValueError, match="handoff_after_tokens"):
        DisaggConfig(handoff_after_tokens=0)
    with pytest.raises(ValueError, match="migration_timeout_s"):
        DisaggConfig(migration_timeout_s=0.0)
    # At least one decode replica must exist.
    with pytest.raises(ValueError, match="num_replicas > prefill"):
        DeploymentConfig(num_replicas=1, disagg=DisaggConfig())
    from ray_tpu.serve.config import AutoscalingConfig
    with pytest.raises(ValueError, match="autoscaling"):
        DeploymentConfig(
            disagg=DisaggConfig(),
            autoscaling_config=AutoscalingConfig(min_replicas=2,
                                                 max_replicas=4))
    # A role other than unified requires the prefix trie — migration
    # is keyed by its chained path hashes.
    set_disagg(DisaggContext(role="prefill"))
    try:
        with pytest.raises(ValueError, match="prefix_cache"):
            LLMServer(CFG, EngineConfig(max_slots=2, max_seq_len=64,
                                        prefix_cache=False),
                      lambda: params)
    finally:
        set_disagg(None)


# -- migration verbs + lease accounting (engine level) -----------------------

def test_migration_lease_pins_against_eviction(params):
    """Pages under a migration lease are eviction-proof: traffic that
    forces refcount-0 LRU eviction must skip them, the export stays
    valid, and after release they evict normally.  Includes the
    cancel-mid-migration path: a stream borrowing leased pages is
    cancelled and the pool accounting still balances."""
    rng = np.random.default_rng(11)
    hot = rng.integers(1, 127, size=2 * PAGE).tolist()
    eng = _engine(params, max_slots=2, num_pages=16)
    try:
        want = greedy_reference(params, hot, 4)
        assert eng.generate(hot, max_new_tokens=4, temperature=0.0) == want
        lease = eng.migration_lease(hot + want)
        assert lease is not None
        # The full-page depth of the finished sequence is leased.
        n_leased = (len(hot) + 4 - 1) // PAGE
        assert len(lease["pages"]) == n_leased
        assert lease["tokens"] == (hot + want)[:n_leased * PAGE]
        _assert_pool_consistent(eng)

        # Cancel mid-migration: a stream borrowing the leased prefix is
        # cancelled; borrow returns, lease stays, nothing leaks.
        s = eng.submit(hot + [9, 9], max_new_tokens=20, temperature=0.0)
        for _tok in s:
            break
        assert s._req.prefix_hit == 2 * PAGE
        s.cancel()
        s.result(timeout_s=120)

        # Eviction pressure: distinct prompts overflow the 12-page pool.
        for i in range(6):
            p = rng.integers(1, 127, size=2 * PAGE + 3).tolist()
            assert eng.generate(p, max_new_tokens=4, temperature=0.0) \
                == greedy_reference(params, p, 4)
        assert eng.stats()["prefix"]["evicted_pages"] > 0
        # The leased pages survived every eviction wave...
        assert set(lease["pages"]) <= eng._prefix.pages()
        assert eng._prefix.leased_pages() == set(lease["pages"])
        _assert_pool_consistent(eng)
        # ...so the export is still content-correct.
        transfer = eng.migration_export(lease["lease_id"], mode="exact")
        kv_transfer.verify_transfer(transfer)
        assert transfer["tokens"] == lease["tokens"]

        assert eng.migration_release(lease["lease_id"]) is True
        assert eng.migration_release(lease["lease_id"]) is False  # idempotent
        assert eng._prefix.leased_pages() == set()
        _assert_pool_consistent(eng)
        # Released pages are evictable again.
        evicted = eng._prefix.evict(eng._num_pages)
        assert set(lease["pages"]) <= set(evicted)
        eng._free_pages.extend(evicted)
        _assert_pool_consistent(eng)
    finally:
        eng.shutdown()


def test_prefix_migration_cold_engine_no_recompute(params):
    """Acceptance: hot prefixes exported from a warm engine and
    ingested by a cold one are admitted as a prefix-cache hit equal to
    the transferred pages — the migrated tokens are never recomputed —
    and exact-mode transfers keep greedy decoding byte-identical."""
    rng = np.random.default_rng(12)
    prompt = rng.integers(1, 127, size=2 * PAGE).tolist()
    want = greedy_reference(params, prompt, 12)
    warm, cold = _engine(params), _engine(params)
    try:
        assert warm.generate(prompt, max_new_tokens=12,
                             temperature=0.0) == want
        cached = warm._prefix.cached_pages
        assert cached == (len(prompt) + 12 - 1) // PAGE

        transfers = warm.export_hot_prefixes(mode="exact")
        assert transfers, "warm engine exported nothing"
        assert max(len(t["hashes"]) for t in transfers) == cached
        out_pages = warm.stats()["kv_migration"]["pages_out"]
        assert out_pages >= cached
        assert warm.stats()["kv_migration"]["bytes_out"] > 0
        # Every lease was released on the way out.
        assert warm._prefix.leased_pages() == set()

        ingested = sum(cold.migration_ingest(t) for t in transfers)
        assert ingested == cached  # dedup: overlapping paths land once
        st = cold.stats()
        assert st["kv_migration"]["pages_in"] == cached
        assert st["prefix"]["cached_pages"] == cached
        # Re-ingesting is a no-op: every depth is already cached.
        assert cold.migration_ingest(transfers[-1]) == 0

        # A probe over the migrated depth is admitted entirely from
        # the transferred pages: prefix_hit == transferred pages, so
        # none of the migrated tokens were recomputed.
        probe = (prompt + want)[:cached * PAGE] + [99, 99, 99]
        s = cold.submit(probe, max_new_tokens=6, temperature=0.0)
        got = s.result(timeout_s=120)
        assert s._req.prefix_hit == cached * PAGE
        assert got == greedy_reference(params, probe, 6)
        # And the original prompt replays byte-identically.
        s2 = cold.submit(prompt, max_new_tokens=12, temperature=0.0)
        assert s2.result(timeout_s=120) == want
        _assert_pool_consistent(cold)
    finally:
        warm.shutdown()
        cold.shutdown()


def test_transfer_rejects_content_mismatch(params):
    """Corrupted tokens (hash chain mismatch) never touch the pool."""
    rng = np.random.default_rng(13)
    prompt = rng.integers(1, 127, size=2 * PAGE).tolist()
    warm, cold = _engine(params), _engine(params)
    try:
        warm.generate(prompt, max_new_tokens=4, temperature=0.0)
        transfer = max(warm.export_hot_prefixes(mode="int8"),
                       key=lambda t: len(t["hashes"]))
        bad = dict(transfer)
        bad["tokens"] = list(transfer["tokens"])
        bad["tokens"][0] ^= 1
        with pytest.raises(ValueError, match="content-identity"):
            cold.migration_ingest(bad)
        assert cold.stats()["kv_migration"]["pages_in"] == 0
        assert cold._prefix.cached_pages == 0
        # The intact transfer still lands.
        assert cold.migration_ingest(transfer) == len(transfer["hashes"])
    finally:
        warm.shutdown()
        cold.shutdown()


# -- disaggregated serving e2e -----------------------------------------------

APP = "llmdisagg"
DEP = "LLMServer"
ROUTER_RING = f"router:{APP}/{DEP}"

N_STREAMS = 6
N_NEW = 12


def _prompts(seed, n):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, 127, size=2 * PAGE).tolist() for _ in range(n)]


def _serve_app(params, *, disagg, adapter_factory=llama_paged_adapter):
    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=16, ignore_reinit_error=True)
    serve.start()
    app = serve.deployment(num_replicas=2, max_ongoing_requests=8,
                           disagg=disagg)(LLMServer).bind(
        CFG,
        EngineConfig(max_slots=8, max_seq_len=64, min_prefill_bucket=16,
                     page_size=PAGE, ragged_batching=True, token_budget=64,
                     decode_chunk=1, prefix_cache=True),
        lambda: params,
        adapter_factory=adapter_factory,
    )
    return serve.run(app, name=APP, route_prefix=None)


def _wait_roles():
    """Poll until the replica set is RUNNING with one prefill and one
    decode replica; returns {role: replica_id}."""
    from ray_tpu.util import state

    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        rows = state.list_replicas()
        running = [r for r in rows if r["state"] == "RUNNING"]
        roles = sorted(r["role"] for r in running)
        if roles == ["decode", "prefill"]:
            return {r["role"]: r["replica_id"] for r in running}
        time.sleep(0.01)
    raise TimeoutError(f"roles never settled: {rows}")


def _replica_handles():
    from ray_tpu.serve.handle import _routers

    router = _routers[(APP, DEP)]
    with router._lock:
        return {rid: info.handle
                for rid, info in router._replicas.items()}


def _consume_streams(gens):
    outs = [[] for _ in gens]
    errs = [None] * len(gens)

    def consume(i):
        try:
            for tok in gens[i]:
                outs[i].append(tok)
        except BaseException as e:
            errs[i] = e

    threads = [threading.Thread(target=consume, args=(i,), daemon=True)
               for i in range(len(gens))]
    for t in threads:
        t.start()
    return outs, errs, threads


def test_disagg_streams_byte_identical_to_unified_oracle(params):
    """Acceptance: greedy streams under disaggregation (prefill
    handoff → exact KV migration → decode-replica resume) emit exactly
    the oracle token sequences; MIGRATING rides the router ring; the
    role column is served deterministically; and a cold replica pulls
    hot prefixes instead of recomputing them."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.core import api
    from ray_tpu.serve import request_events
    from ray_tpu.util import state

    prompts = _prompts(21, N_STREAMS)
    wants = [greedy_reference(params, p, N_NEW) for p in prompts]
    pull_prompts = _prompts(22, 2)
    pull_wants = [greedy_reference(params, p, 2) for p in pull_prompts]

    handle = _serve_app(
        params,
        disagg={"prefill_replicas": 1, "transfer": "exact",
                "handoff_after_tokens": 2})
    try:
        roles = _wait_roles()

        # -- `raytpu list replicas` role column: deterministic --------
        rows1 = state.list_replicas()
        rows2 = state.list_replicas()
        assert rows1 == rows2, "list_replicas is not deterministic"
        assert set(rows1[0]) == {"app", "deployment", "replica_id",
                                 "state", "role", "shard_group",
                                 "mesh_shape", "members",
                                 "target_groups", "actual_groups",
                                 "autoscale", "ctl_epoch",
                                 "last_recovery"}
        assert sorted(r["role"] for r in rows1) == ["decode", "prefill"]
        from ray_tpu.scripts import cli
        assert "role" in cli._LIST_ROUTES["replicas"][1]

        # -- Phase A: short streams stay local on the prefill replica
        # (requested <= handoff_after_tokens), so only it gets warm.
        shandle = handle.options(stream=True)
        for p, w in zip(pull_prompts, pull_wants):
            assert shandle.remote(
                {"tokens": p, "max_new_tokens": 2, "temperature": 0.0}
            ).result(timeout_s=300) == w
        handles = _replica_handles()  # router exists after first request
        assert set(handles) == set(roles.values())

        def _dstats(role):
            return api.get(handles[roles[role]].handle_request.remote(
                "disagg_stats", (), {}), timeout=60)

        def _stats(role):
            return api.get(handles[roles[role]].handle_request.remote(
                "stats", (), {}), timeout=60)

        ds = _dstats("prefill")
        assert ds["role"] == "prefill"
        assert ds["handoffs"]["local"] >= 2
        assert ds["handoffs"]["migrated"] == 0
        warm_stats = _stats("prefill")
        assert warm_stats["prefix"]["cached_pages"] > 0
        assert _dstats("decode")["role"] == "decode"
        assert _stats("decode")["prefix"]["cached_pages"] == 0

        # -- Cold pull: the decode replica ingests the prefill
        # replica's hot prefixes once its summary has propagated.
        deadline = time.monotonic() + 120
        pulled = 0
        while time.monotonic() < deadline:
            pulled = api.get(handles[roles["decode"]].handle_request
                             .remote("pull_prefix_cache", (256,), {},
                                     None), timeout=60)
            if pulled > 0:
                break
            time.sleep(0.25)
        assert pulled == warm_stats["prefix"]["cached_pages"], \
            "cold replica did not ingest the survivor's hot prefixes"
        cold_stats = _stats("decode")
        assert cold_stats["kv_migration"]["pages_in"] == pulled
        assert cold_stats["prefix"]["cached_pages"] >= pulled

        # -- Phase B: long streams run the full handoff protocol -----
        retries_before = _metric_total(
            r"raytpu_serve_request_retries_total")
        gens = [shandle.remote({"tokens": prompts[i],
                                "max_new_tokens": N_NEW,
                                "temperature": 0.0})
                for i in range(N_STREAMS)]
        outs, errs, threads = _consume_streams(gens)
        for t in threads:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in threads), \
            f"streams hung: {[len(o) for o in outs]}"
        assert errs == [None] * N_STREAMS, f"streams failed: {errs}"
        assert outs == wants  # byte-identical to the unified oracle

        ds = _dstats("prefill")
        assert ds["handoffs"]["migrated"] == N_STREAMS
        assert ds["handoffs"]["failed"] == 0
        assert ds["requests"] >= 2 + N_STREAMS
        assert ds["kv_migration"]["pages_out"] > 0
        assert ds["kv_migration"]["bytes_out"] > 0
        dd = _dstats("decode")
        assert dd["kv_migration"]["pages_in"] > pulled  # handoff pages
        assert dd["requests"] >= N_STREAMS  # resumed streams
        # A handoff is a SUCCESSFUL attempt, not a failure: the
        # router-side retries counter must not move.
        assert _metric_total(
            r"raytpu_serve_request_retries_total") == retries_before

        # -- Router ring: every stream records the planned MIGRATING
        # transition (attempt bumped, retries NOT charged) and ends
        # FINISHED with the handoff in its attempt history.
        rows = [r for r in request_events.snapshot_rows()
                if r["engine"] == ROUTER_RING]
        by_id = {r["request_id"]: r for r in rows}
        for g in gens:
            r = by_id[g.request_id]
            assert r["state"] == "FINISHED"
            assert "MIGRATING" in r["state_ts"]
            assert r["attempt"] >= 1
            mig = [a for a in r["attempts"]
                   if str(a.get("reason", "")).startswith("migrated:")]
            assert mig and mig[0]["reason"].endswith(roles["decode"])
    finally:
        serve.shutdown()
        ray_tpu.shutdown()


def _slow_paged_adapter_factory(cfg):
    """Paged adapter with a throttled ragged step so the prefill phase
    of a handoff spans an observable window and the kill reliably
    lands before the handoff completes (jax.debug.callback: the step is
    traced under jit, a bare sleep would fire at trace time only)."""
    import dataclasses

    base = llama_paged_adapter(cfg)

    def slow_step(*args, **kwargs):
        jax.debug.callback(lambda: time.sleep(0.03), ordered=True)
        return base.ragged_step(*args, **kwargs)

    return dataclasses.replace(base, ragged_step=slow_step)


def test_disagg_prefill_kill_falls_back_to_recompute(params):
    """Acceptance: SIGKILL the prefill replica while streams are
    mid-handoff — every stream still finishes byte-identical to the
    oracle via the continuation replay (local recompute on a
    survivor), and the ring records the RETRYING/MIGRATING story."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.core import api
    from ray_tpu.serve import request_events
    from ray_tpu.utils.test_utils import ReplicaKiller

    prompts = _prompts(31, N_STREAMS)
    wants = [greedy_reference(params, p, N_NEW) for p in prompts]

    handle = _serve_app(
        params,
        disagg={"prefill_replicas": 1, "transfer": "exact",
                "handoff_after_tokens": 6},
        adapter_factory=_slow_paged_adapter_factory)
    try:
        roles = _wait_roles()
        # Prime the router (created lazily on first request) so the
        # replica handles are inspectable; short request stays local.
        handle.options(stream=True).remote(
            {"tokens": [1, 2, 3], "max_new_tokens": 1,
             "temperature": 0.0}).result(timeout_s=300)
        handles = _replica_handles()

        shandle = handle.options(stream=True, max_retries=8)
        gens = [shandle.remote({"tokens": prompts[i],
                                "max_new_tokens": N_NEW,
                                "temperature": 0.0})
                for i in range(N_STREAMS)]
        outs, errs, threads = _consume_streams(gens)

        # Wait until every stream is decoding on the prefill replica
        # (past prefill, before the 6-token handoff point at 0.03 s a
        # step), then SIGKILL it — mid-handoff by construction.
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            if all(len(o) >= 1 for o in outs):
                break
            time.sleep(0.002)
        assert all(len(o) >= 1 for o in outs), "streams never started"
        killer = ReplicaKiller(api.runtime(), seed=0)
        victim = handles[roles["prefill"]]
        assert killer.kill_one(actor_id=victim._actor_id) is not None

        for t in threads:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in threads), \
            f"streams hung after kill: {[len(o) for o in outs]}"
        assert errs == [None] * N_STREAMS, f"streams failed: {errs}"
        assert outs == wants  # replay recomputed, not one token lost

        rows = [r for r in request_events.snapshot_rows()
                if r["engine"] == ROUTER_RING]
        by_id = {r["request_id"]: r for r in rows}
        retried = 0
        for g in gens:
            r = by_id[g.request_id]
            assert r["state"] == "FINISHED"
            # Every stream either hit the kill (RETRYING + local
            # recompute) or had already handed off (MIGRATING).
            assert ("RETRYING" in r["state_ts"]
                    or "MIGRATING" in r["state_ts"]), r["state_ts"]
            retried += "RETRYING" in r["state_ts"]
        assert retried > 0, "kill landed but nothing retried"
    finally:
        serve.shutdown()
        ray_tpu.shutdown()
