"""A deployment-graph application imported by the serve YAML schema
test (tests/test_serve_graph.py::test_graph_from_yaml_schema)."""

from ray_tpu import serve


@serve.deployment
class Words:
    def split(self, text):
        return text.split()


@serve.deployment
class Scale:
    def __init__(self, k):
        self.k = k

    def times(self, tokens):
        return self.k * float(len(tokens))


with serve.InputNode() as _inp:
    app = serve.build_graph_app(
        Scale.bind(3.0).times.bind(Words.bind().split.bind(_inp)),
        driver_name="YamlGraphDriver")
