"""Autoscaler (parity: autoscaler/_private/autoscaler.py update loop,
resource_demand_scheduler.py bin-packing, FakeMultiNodeProvider)."""

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import (
    AutoscalerMonitor,
    FakeNodeProvider,
    NodeTypeConfig,
    ResourceDemandScheduler,
    StandardAutoscaler,
)


@pytest.fixture
def rt():
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    yield ray_tpu._api().runtime()
    ray_tpu.shutdown()


def test_bin_packing_first_fit():
    sched = ResourceDemandScheduler([
        NodeTypeConfig("small", {"CPU": 4}, max_workers=10),
        NodeTypeConfig("big", {"CPU": 16}, max_workers=10),
    ])
    # Eight 2-CPU demands pack into two 4-CPU nodes... they fit 2 each.
    out = sched.get_nodes_to_launch([{"CPU": 2}] * 8, {}, global_max=20)
    assert out == {"small": 4}
    # A 10-CPU demand needs the big type.
    out = sched.get_nodes_to_launch([{"CPU": 10}], {}, global_max=20)
    assert out == {"big": 1}
    # Mixed: the big node's leftover absorbs small demands.
    out = sched.get_nodes_to_launch(
        [{"CPU": 10}, {"CPU": 2}, {"CPU": 2}], {}, global_max=20
    )
    assert out == {"big": 1}


def test_bin_packing_respects_caps():
    sched = ResourceDemandScheduler(
        [NodeTypeConfig("small", {"CPU": 4}, max_workers=2)]
    )
    out = sched.get_nodes_to_launch([{"CPU": 4}] * 5, {}, global_max=20)
    assert out == {"small": 2}  # per-type cap
    out = sched.get_nodes_to_launch([{"CPU": 4}] * 5, {"small": 1},
                                    global_max=2)
    assert out == {"small": 1}  # global cap counts existing nodes
    # Infeasible demands are skipped, not looped on.
    assert sched.get_nodes_to_launch([{"GPU": 1}], {}, global_max=20) == {}


def test_autoscaler_scales_up_for_pending_tasks(rt):
    provider = FakeNodeProvider(rt)
    autoscaler = StandardAutoscaler(
        provider,
        [NodeTypeConfig("worker", {"CPU": 8, "memory": 16 * 1024**3},
                        max_workers=4)],
        runtime=rt, idle_node_timeout_s=60,
    )

    @ray_tpu.remote(num_cpus=8)
    def heavy():
        time.sleep(1.0)  # long enough to observe the queue
        return "done"

    launched, _ = autoscaler.update()
    assert launched == {}  # no demand yet

    # Head has 2 CPUs; seed one 8-CPU node so the task class is
    # feasible, then oversubscribe it: 1 runs, 2 queue.
    node = rt.add_node({"CPU": 8, "memory": 16 * 1024**3})
    refs = [heavy.remote() for _ in range(3)]
    time.sleep(0.3)
    launched, _ = autoscaler.update()
    assert launched.get("worker") == 2  # one node per queued task
    assert ray_tpu.get(refs, timeout=15) == ["done"] * 3
    rt.kill_node(node)


def test_autoscaler_min_workers_floor(rt):
    provider = FakeNodeProvider(rt)
    autoscaler = StandardAutoscaler(
        provider,
        [NodeTypeConfig("base", {"CPU": 4, "memory": 8 * 1024**3},
                        min_workers=2, max_workers=4)],
        runtime=rt,
    )
    launched, _ = autoscaler.update()
    assert launched == {"base": 2}
    assert len(provider.non_terminated_nodes()) == 2
    launched, _ = autoscaler.update()
    assert launched == {}  # floor satisfied


def test_autoscaler_terminates_idle_nodes(rt):
    provider = FakeNodeProvider(rt)
    autoscaler = StandardAutoscaler(
        provider,
        [NodeTypeConfig("worker", {"CPU": 4, "memory": 8 * 1024**3},
                        min_workers=1, max_workers=4)],
        runtime=rt, idle_node_timeout_s=0.1,
    )
    for _ in range(3):
        provider.create_node("worker", {"CPU": 4, "memory": 8 * 1024**3}, {})
    time.sleep(0.15)
    autoscaler.update()          # records idle-since
    time.sleep(0.15)
    _, terminated = autoscaler.update()
    # Scales down to the min_workers floor, not to zero.
    assert len(provider.non_terminated_nodes()) == 1
    assert len(terminated) == 2


def test_autoscaler_monitor_loop(rt):
    provider = FakeNodeProvider(rt)
    autoscaler = StandardAutoscaler(
        provider,
        [NodeTypeConfig("auto", {"CPU": 4, "memory": 8 * 1024**3},
                        min_workers=1, max_workers=2)],
        runtime=rt,
    )
    mon = AutoscalerMonitor(autoscaler, interval_s=0.05).start()
    try:
        deadline = time.time() + 5
        while (not provider.non_terminated_nodes()
               and time.time() < deadline):
            time.sleep(0.05)
        assert provider.non_terminated_nodes()
    finally:
        mon.stop()
