"""Native C++ scheduler (parity: src/ray/common/scheduling fixed-point
ledgers + raylet/scheduling hybrid/spread policies, built per
ray_tpu/_native/scheduler.cc)."""

import threading

import pytest

import ray_tpu
from ray_tpu.core.native_scheduler import (
    HYBRID,
    SPREAD,
    NativeClusterScheduler,
)


@pytest.fixture
def sched():
    s = NativeClusterScheduler()
    yield s
    s.close()


def test_ledger_roundtrip(sched):
    sched.add_node(1, {"CPU": 4, "TPU": 2.5})
    assert sched.available(1, "CPU") == 4.0
    assert sched.try_acquire(1, {"CPU": 2, "TPU": 0.5})
    assert sched.available(1, "CPU") == 2.0
    assert sched.available(1, "TPU") == 2.0
    assert not sched.try_acquire(1, {"CPU": 3})
    sched.release(1, {"CPU": 2, "TPU": 0.5})
    assert sched.available(1, "CPU") == 4.0


def test_fixed_point_no_drift(sched):
    """0.1 repeatedly acquired/released must come back exactly (parity:
    fixed_point.h — the reason the reference avoids float resources)."""
    sched.add_node(1, {"CPU": 1.0})
    for _ in range(10):
        assert sched.try_acquire(1, {"CPU": 0.1})
    assert sched.available(1, "CPU") == 0.0
    assert not sched.try_acquire(1, {"CPU": 0.1})
    for _ in range(10):
        sched.release(1, {"CPU": 0.1})
    assert sched.available(1, "CPU") == 1.0


def test_hybrid_packs_then_spreads(sched):
    sched.add_node(1, {"CPU": 4})
    sched.add_node(2, {"CPU": 4})
    # Below the 0.5 threshold: pack onto node 1 in stable order.
    assert sched.pick_and_acquire({"CPU": 1}, HYBRID) == 1
    assert sched.pick_and_acquire({"CPU": 1}, HYBRID) == 1
    # Node 1 now at 0.5 utilization → next lands on node 2.
    assert sched.pick_and_acquire({"CPU": 1}, HYBRID) == 2


def test_spread_least_utilized(sched):
    sched.add_node(1, {"CPU": 4})
    sched.add_node(2, {"CPU": 4})
    sched.try_acquire(1, {"CPU": 3})
    assert sched.pick_and_acquire({"CPU": 1}, SPREAD) == 2


def test_candidates_and_dead_nodes(sched):
    sched.add_node(1, {"CPU": 4})
    sched.add_node(2, {"CPU": 4})
    assert sched.pick_and_acquire({"CPU": 1}, HYBRID,
                                  candidates=[2]) == 2
    sched.kill_node(2)
    assert sched.pick_and_acquire({"CPU": 1}, HYBRID,
                                  candidates=[2]) is None
    assert sched.cluster_can_fit({"CPU": 4})
    assert not sched.cluster_can_fit({"CPU": 8})
    assert not sched.cluster_can_fit({"GPU": 1})


def test_concurrent_acquire_never_oversubscribes(sched):
    sched.add_node(1, {"CPU": 50})
    wins = []

    def worker():
        got = 0
        for _ in range(100):
            if sched.try_acquire(1, {"CPU": 1}):
                got += 1
        wins.append(got)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sum(wins) == 50
    assert sched.available(1, "CPU") == 0.0


def test_runtime_uses_native_scheduler():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    try:
        rt = ray_tpu._api().runtime()
        assert rt._native_sched is not None, \
            "native scheduler must build in this image (g++ present)"

        @ray_tpu.remote
        def f():
            return 1

        assert ray_tpu.get([f.remote() for _ in range(8)]) == [1] * 8
        # Ledger returns to full after the burst.  Release happens in the
        # task thread's finally block, which can lag the result seal by a
        # beat — poll briefly.
        import time

        deadline = time.time() + 5
        while time.time() < deadline:
            if ray_tpu.available_resources().get("CPU") == 4.0:
                break
            time.sleep(0.02)
        assert ray_tpu.available_resources()["CPU"] == 4.0
    finally:
        ray_tpu.shutdown()


def test_pure_python_fallback_runtime():
    """No C++ toolchain → the runtime must still fully work (ledger,
    tasks, placement groups) on the Python ResourcePool path."""
    import unittest.mock as mock

    import ray_tpu
    from ray_tpu.util import placement_group

    with mock.patch(
        "ray_tpu.core.native_scheduler.NativeClusterScheduler",
        side_effect=RuntimeError("no g++"),
    ):
        ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
        try:
            rt = ray_tpu._api().runtime()
            assert rt._native_sched is None

            @ray_tpu.remote
            def f():
                return 5

            assert ray_tpu.get([f.remote() for _ in range(4)]) == [5] * 4
            pg = placement_group([{"CPU": 1}])
            ray_tpu.get(pg.ready())
        finally:
            ray_tpu.shutdown()
