"""Stall telemetry: step-wall watermark, admission-queue age, and the
N-x-median stall warning in serve/llm_engine.py (the instrumentation
BENCH_r05's 1.14B collapse was missing — p95 TTFT 200x p50 with no
engine-side record of where the time went)."""

import queue
import time
import types

import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import llama
from ray_tpu.serve import llm_engine
from ray_tpu.serve.llm_engine import LLMEngine, _telemetry


class _Gauge:
    def __init__(self):
        self.value = None

    def set(self, v):
        self.value = v


def _shim(paged=True):
    """A bare object carrying just the state _note_step_time and
    _admission_queue_age touch, so the helpers are unit-testable
    without building an engine."""
    from collections import deque

    ns = types.SimpleNamespace()
    ns._step_walls = deque(maxlen=64)
    ns._step_wall_hw = 0.0
    ns._stall_events = 0
    ns._tm = {"step_wall": _Gauge(), "queue_age": _Gauge()}
    ns._slot_req = {}
    ns._waiting = queue.Queue()
    ns._backlog = []
    ns._paged = paged
    return ns


def test_note_step_time_watermark_and_stall():
    ns = _shim()
    # 20 normal chunks at ~1 ms/step: no warning, watermark tracks max.
    for i in range(20):
        warned = LLMEngine._note_step_time(ns, 0.008 + 0.0001 * i, 8)
        assert not warned
    assert ns._tm["step_wall"].value == pytest.approx(
        (0.008 + 0.0019) / 8)
    # One 10x stall: warned, and the watermark jumps to it.
    warned = LLMEngine._note_step_time(ns, 0.080, 8)
    assert warned
    assert ns._tm["step_wall"].value == pytest.approx(0.010)


def test_note_step_time_needs_history():
    """The first few chunks establish the median — no warning before
    there is a baseline to deviate from."""
    ns = _shim()
    for _ in range(7):
        assert not LLMEngine._note_step_time(ns, 0.001, 1)
    # 8th sample has 7 of history — still below the 8-sample floor.
    assert not LLMEngine._note_step_time(ns, 1.0, 1)
    # With >=8 samples of history the same stall now warns.
    assert LLMEngine._note_step_time(ns, 1.0, 1)


def test_admission_queue_age():
    ns = _shim()
    assert LLMEngine._admission_queue_age(ns) == 0.0
    now = time.monotonic()
    ns._waiting.put(types.SimpleNamespace(submitted_at=now - 2.0))
    ns._backlog.append(types.SimpleNamespace(submitted_at=now - 5.0))
    age = LLMEngine._admission_queue_age(ns)
    assert 4.9 < age < 6.0  # the backlog request is the oldest
    # Non-paged engines have no backlog to scan.
    ns2 = _shim(paged=False)
    ns2._waiting.put(types.SimpleNamespace(submitted_at=now - 1.0))
    assert 0.9 < LLMEngine._admission_queue_age(ns2) < 2.0


def test_engine_run_populates_gauges_with_clean_grammar():
    """End-to-end: a tiny paged-engine run sets both new gauges, and
    the resulting exposition passes the repo metric-name contract."""
    import importlib.util
    import pathlib

    from ray_tpu.serve.llm_engine import EngineConfig, llama_paged_adapter
    from ray_tpu.util import metrics

    cfg = llama.LlamaConfig(
        vocab_size=128, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
        mlp_dim=64, max_seq_len=128, remat=False, dtype=jnp.float32,
        param_dtype=jnp.float32)
    params = llama.init_params(__import__("jax").random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    eng = LLMEngine(
        params, llama_paged_adapter(cfg),
        EngineConfig(max_slots=2, max_seq_len=128, decode_chunk=4,
                     max_new_tokens_default=6, min_prefill_bucket=64,
                     page_size=64))
    eng.generate(rng.integers(0, cfg.vocab_size, 20).tolist())
    eng.shutdown()

    text = metrics.export_prometheus()
    assert "raytpu_serve_step_wall_seconds" in text
    assert "raytpu_serve_admission_queue_age_seconds" in text
    # The decode path ran, so the watermark must be a real positive.
    samples = _telemetry()["step_wall"]._samples()
    assert samples and samples[0][2] > 0

    path = (pathlib.Path(__file__).resolve().parent.parent
            / "scripts" / "check_metrics.py")
    spec = importlib.util.spec_from_file_location("check_metrics", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.check_exposition(text) == []
