"""Headline benchmark: Llama train-step + LLM-serving throughput on chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tokens/sec/chip", "vs_baseline": N,
   "extra": {...}}

``value`` is tokens/sec/chip of the full jitted train step (fwd+bwd+
AdamW) on a ~319M-param Llama sized for a single v5e chip, with
TPU-first choices: bf16 compute, head_dim 128 (8 heads — the MXU's
contraction dim wants 128; same param count and 6N flops as the
16-head/64-dim variant, +40% throughput), Pallas flash attention,
dots-saveable remat, bf16 Adam first moment, donated step buffers.

``vs_baseline`` compares against a deliberately un-TPU-optimized
variant — float32 compute, full remat — i.e. what a straight port that
ignores MXU dtype and HBM management would get.  (The reference
publishes no absolute tokens/sec itself; see BASELINE.md.)

``extra`` carries the other north stars (BASELINE.json):
  - llama_1b: a 1.14B-param single-chip config (bf16 master, full
    remat, chunked cross-entropy — never materializes [B,S,V] logits)
  - serving: continuous-batching LLM engine req/s + p50/p95 TTFT on
    the same chip (prompt 128, gen 32, 8 slots).
"""

from __future__ import annotations

import dataclasses
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models import llama
from ray_tpu.parallel import MeshSpec
from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig, default_optimizer

BATCH = 8
SEQ = 2048

BENCH_CFG = llama.LlamaConfig(
    vocab_size=32_768,
    dim=1024,
    n_layers=16,
    n_heads=8,       # head_dim 128: full MXU contraction (v5e tile 128)
    n_kv_heads=4,
    mlp_dim=4096,
    max_seq_len=SEQ,
)

# 1B-class config for the single-chip headroom point: bf16 master params
# (f32 states would need 14 GB before activations on a 16 GB chip),
# full per-layer remat, sequence-chunked CE.
BENCH_1B_CFG = llama.LlamaConfig(
    vocab_size=32_768,
    dim=2048,
    n_layers=16,
    n_heads=16,
    n_kv_heads=8,
    mlp_dim=8192,
    max_seq_len=SEQ,
    param_dtype=jnp.bfloat16,
    remat_policy="full",
    loss_chunk=512,
)

# bf16 peak per chip, for MFU reporting
PEAK_FLOPS = {
    "v5e": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6e": 918e12,
    "cpu": 1e12,  # nominal; MFU is meaningless on CPU
}


def _make_trainer(cfg, devices):
    return JaxTrainer(
        init_params=lambda r: llama.init_params(r, cfg),
        loss_fn=lambda p, b: llama.loss_fn(p, b, cfg),
        params_axes=llama.logical_axes(cfg),
        batch_axes={"tokens": ("batch", None)},
        optimizer=default_optimizer(1e-4, warmup_steps=10,
                                    mu_dtype=jnp.bfloat16),
        scaling_config=ScalingConfig(
            mesh_spec=MeshSpec(dp=1, fsdp=len(devices)), devices=devices
        ),
        run_config=RunConfig(report_every=1_000_000),
    )


def _measure(cfg, devices, *, steps: int, batch: int = None,
             warmup: int = 2) -> float:
    """Tokens/sec of the jitted train step (post-warmup)."""
    batch = batch or BATCH
    trainer = _make_trainer(cfg, devices)
    rng = np.random.default_rng(0)

    def batches():
        while True:
            yield {
                "tokens": rng.integers(
                    0, cfg.vocab_size, (batch, SEQ), dtype=np.int64
                ).astype(np.int32)
            }

    it = batches()
    with trainer.mesh:
        state = trainer.state
        step = trainer._step_fn
        # Pre-stage batches on device: host→device transfers ride a
        # potentially slow transport and real input pipelines overlap them
        # (ray_tpu.data prefetch), so they don't belong in the step timing.
        staged = [trainer.shard_batch(next(it)) for _ in range(min(steps, 4))]
        for _ in range(warmup):
            state, metrics = step(state, staged[0])
        # device_get, not block_until_ready: some PJRT transports (e.g. the
        # axon tunnel) return from block_until_ready before execution ends;
        # a host transfer of a value that depends on the whole step is the
        # only reliable fence.
        float(jax.device_get(metrics["loss"]))
        t0 = time.perf_counter()
        for i in range(steps):
            state, metrics = step(state, staged[i % len(staged)])
        float(jax.device_get(metrics["loss"]))
        dt = time.perf_counter() - t0
    return batch * SEQ * steps / dt


def _measure_serving(cfg, *, n_requests: int = 48, prompt_len: int = 128,
                     gen: int = 32) -> dict:
    """Continuous-batching engine (paged KV cache): req/s + TTFT."""
    from ray_tpu.serve.llm_engine import (
        EngineConfig,
        LLMEngine,
        llama_paged_adapter,
    )

    slots = 16
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    eng = LLMEngine(
        params, llama_paged_adapter(cfg),
        EngineConfig(max_slots=slots, max_seq_len=512, decode_chunk=16,
                     max_new_tokens_default=gen, page_size=64),
    )
    rng = np.random.default_rng(1)
    prompts = [rng.integers(0, cfg.vocab_size, prompt_len).tolist()
               for _ in range(n_requests)]
    # Warm every compiled variant the run will hit off the clock:
    # batched prefill at this bucket, decode chunks 16/4/1.
    warm = [eng.submit(p, max_new_tokens=gen) for p in prompts[:slots]]
    for s in warm:
        s.result(timeout_s=600)
    t0 = time.perf_counter()
    streams = [eng.submit(p, max_new_tokens=gen, temperature=0.0)
               for p in prompts]
    outs = [s.result(timeout_s=600) for s in streams]
    dt = time.perf_counter() - t0
    ttfts = sorted(s._req.ttft_s for s in streams
                   if s._req.ttft_s is not None)
    eng.shutdown()
    assert all(len(o) == gen for o in outs)
    p = lambda q: round(ttfts[min(len(ttfts) - 1, int(q * len(ttfts)))] * 1e3, 1)
    return {
        "req_per_s": round(n_requests / dt, 2),
        "decode_tokens_per_s": round(n_requests * gen / dt, 1),
        "ttft_p50_ms": p(0.50),
        "ttft_p95_ms": p(0.95),
        "prompt_len": prompt_len,
        "gen": gen,
        "slots": slots,
    }


def main():
    devices = jax.devices()
    on_tpu = devices[0].platform != "cpu"
    steps = 10 if on_tpu else 2
    cfg = BENCH_CFG if on_tpu else dataclasses.replace(
        BENCH_CFG, dim=256, n_layers=4, n_heads=8, n_kv_heads=4, mlp_dim=1024
    )

    tps = _measure(cfg, devices, steps=steps)
    # Baseline: same step in float32 — the throughput of a port that
    # ignores the MXU's bf16 preference.  (f32 *without* remat, the truly
    # naive variant, OOMs outright at this size: 34 GB of attention probs.)
    baseline_cfg = dataclasses.replace(cfg, dtype=jax.numpy.float32,
                                       remat_policy="full")
    try:
        baseline_tps = _measure(baseline_cfg, devices, steps=max(2, steps // 3))
    except Exception:
        baseline_tps = float("nan")

    n_chips = len(devices)
    tps_chip = tps / n_chips
    from ray_tpu.parallel.mesh import detect_topology

    gen = detect_topology().generation
    peak = PEAK_FLOPS.get(gen, 1e12)
    flops_per_token = 6 * cfg.num_params()
    mfu = tps_chip * flops_per_token / peak

    extra = {
        "chips": n_chips,
        "platform": gen,
        "mfu": round(mfu, 4),
        "batch": BATCH,
        "seq": SEQ,
        "params_m": round(cfg.num_params() / 1e6, 1),
    }

    if on_tpu:
        # North star #1: the largest single-chip config (≥1B params).
        try:
            cfg_1b = BENCH_1B_CFG
            tps_1b = _measure(cfg_1b, devices, steps=4) / n_chips
            extra["llama_1b"] = {
                "params_m": round(cfg_1b.num_params() / 1e6, 1),
                "tokens_per_sec_per_chip": round(tps_1b, 1),
                "mfu": round(tps_1b * 6 * cfg_1b.num_params() / peak, 4),
            }
        except Exception as e:
            extra["llama_1b"] = {"error": repr(e)[:120]}
        # North star #2: serving req/s + TTFT (continuous batching).
        try:
            extra["serving"] = _measure_serving(
                dataclasses.replace(cfg, max_seq_len=512))
        except Exception as e:
            extra["serving"] = {"error": repr(e)[:120]}

    result = {
        "metric": f"llama_{cfg.num_params()/1e6:.0f}M_train_tokens_per_sec_per_chip",
        "value": round(tps_chip, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(tps / baseline_tps, 3) if baseline_tps == baseline_tps else None,
        "extra": extra,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
