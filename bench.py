"""Headline benchmark: Llama train-step + LLM-serving throughput on chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tokens/sec/chip", "vs_baseline": N,
   "extra": {...}}

``value`` is tokens/sec/chip of the full jitted train step (fwd+bwd+
AdamW) on a ~319M-param Llama sized for a single v5e chip, with
TPU-first choices: bf16 compute, head_dim 128 (8 heads — the MXU's
contraction dim wants 128; same param count and 6N flops as the
16-head/64-dim variant, +40% throughput), Pallas flash attention,
dots-saveable remat, bf16 Adam first moment, donated step buffers.

``vs_baseline`` compares against a deliberately un-TPU-optimized
variant — float32 compute, full remat — i.e. what a straight port that
ignores MXU dtype and HBM management would get.  (The reference
publishes no absolute tokens/sec itself; see BASELINE.md.)

``extra`` carries the other north stars (BASELINE.json):
  - llama_1b: a 1.14B-param single-chip config (bf16 master, full
    remat, chunked cross-entropy — never materializes [B,S,V] logits)
  - serving: continuous-batching LLM engine req/s + p50/p95 TTFT on
    the same chip (prompt 128, gen 32, 8 slots).
"""

from __future__ import annotations

import dataclasses
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models import llama
from ray_tpu.parallel import MeshSpec
from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig, default_optimizer

BATCH = 8
SEQ = 2048

BENCH_CFG = llama.LlamaConfig(
    vocab_size=32_768,
    dim=1024,
    n_layers=16,
    n_heads=8,       # head_dim 128: full MXU contraction (v5e tile 128)
    n_kv_heads=4,
    mlp_dim=4096,
    max_seq_len=SEQ,
)

# 1B-class config for the single-chip headroom point: bf16 master params
# (f32 states would need 14 GB before activations on a 16 GB chip),
# full per-layer remat, sequence-chunked CE.
BENCH_1B_CFG = llama.LlamaConfig(
    vocab_size=32_768,
    dim=2048,
    n_layers=16,
    n_heads=16,
    n_kv_heads=8,
    mlp_dim=8192,
    max_seq_len=SEQ,
    param_dtype=jnp.bfloat16,
    remat_policy="full",
    loss_chunk=512,
)

# Measured multi-billion point (VERDICT r4 item 6: the largest config
# that truly fits 16 GB, not an extrapolation): ~2.24B params with
# bf16 master weights + block-wise INT8 Adam states (train/optim8.py —
# 2 bytes/param of optimizer state), full remat, chunked CE.
BENCH_2B_CFG = llama.LlamaConfig(
    vocab_size=32_768,
    dim=2560,
    n_layers=22,
    n_heads=20,
    n_kv_heads=4,
    mlp_dim=10240,
    max_seq_len=SEQ,
    param_dtype=jnp.bfloat16,
    remat_policy="full",
    loss_chunk=512,
)

# Mixed-length prompt ladders: the serving knee measured on REALISTIC
# traffic instead of the single prompt_len=128 point — with ragged
# batching on, prefill chunks and decode rows share one token-budgeted
# device step, so TTFT at the knee should hold as prompts diversify.
# Weights are per-REQUEST sampling probabilities.
PROMPT_MIXES = {
    # interactive chat: short prompts, tight TTFT expectations
    "short_chat": {"lens": (32, 64, 128), "weights": (0.5, 0.3, 0.2)},
    # retrieval-augmented: mostly long stuffed contexts
    "long_rag": {"lens": (512, 1024, 1536), "weights": (0.3, 0.5, 0.2)},
    # bimodal: chat traffic with occasional huge pastes — the mix that
    # head-of-line-blocks a two-program (prefill|decode) engine
    "bursty": {"lens": (32, 64, 1536), "weights": (0.55, 0.3, 0.15)},
    # Zipfian multi-tenant conversations: popular tenants share a
    # page-aligned system-prompt + history prefix (Zipf(alpha) over
    # tenants picks whose), suffixes are fresh per request, and a
    # private_frac slice belongs to one-off tenants (always-cold
    # baseline).  Serves with EngineConfig.prefix_cache — the mix the
    # radix-tree prefix cache exists for; the record grows a "prefix"
    # block (hit ratio, TTFT-by-hit-depth vs cold).
    "zipf_chat": {"lens": (192, 256, 320), "weights": (0.3, 0.4, 0.3),
                  "zipf": {"tenants": 8, "alpha": 1.1,
                           "shared_frac": 0.6, "private_frac": 0.25}},
}

# bf16 peak per chip, for MFU reporting
PEAK_FLOPS = {
    "v5e": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6e": 918e12,
    "cpu": 1e12,  # nominal; MFU is meaningless on CPU
}


def _make_trainer(cfg, devices, optimizer=None, trainer_config=None):
    return JaxTrainer(
        init_params=lambda r: llama.init_params(r, cfg),
        loss_fn=lambda p, b: llama.loss_fn(p, b, cfg),
        params_axes=llama.logical_axes(cfg),
        batch_axes={"tokens": ("batch", None)},
        optimizer=optimizer or default_optimizer(
            1e-4, warmup_steps=10, mu_dtype=jnp.bfloat16),
        scaling_config=ScalingConfig(
            mesh_spec=MeshSpec(dp=1, fsdp=len(devices)), devices=devices
        ),
        run_config=RunConfig(report_every=1_000_000),
        trainer_config=trainer_config,
    )


def _measure(cfg, devices, *, steps: int, batch: int = None,
             warmup: int = 2, optimizer=None, trainer_config=None,
             extras: dict = None) -> float:
    """Tokens/sec of the jitted train step (post-warmup).

    ``extras`` (when a dict) receives the live trainer, so callers can
    read the trained state's actual shardings afterwards (the 8B ZeRO
    rung reports opt-state bytes/param straight from the arrays)."""
    batch = batch or BATCH
    trainer = _make_trainer(cfg, devices, optimizer, trainer_config)
    if extras is not None:
        extras["trainer"] = trainer
    rng = np.random.default_rng(0)

    def batches():
        while True:
            yield {
                "tokens": rng.integers(
                    0, cfg.vocab_size, (batch, SEQ), dtype=np.int64
                ).astype(np.int32)
            }

    it = batches()
    with trainer.mesh:
        state = trainer.state
        step = trainer._step_fn
        # Pre-stage batches on device: host→device transfers ride a
        # potentially slow transport and real input pipelines overlap them
        # (ray_tpu.data prefetch), so they don't belong in the step timing.
        staged = [trainer.shard_batch(next(it)) for _ in range(min(steps, 4))]
        for _ in range(warmup):
            state, metrics = step(state, staged[0])
        # device_get, not block_until_ready: some PJRT transports (e.g. the
        # axon tunnel) return from block_until_ready before execution ends;
        # a host transfer of a value that depends on the whole step is the
        # only reliable fence.
        float(jax.device_get(metrics["loss"]))
        t0 = time.perf_counter()
        for i in range(steps):
            state, metrics = step(state, staged[i % len(staged)])
        float(jax.device_get(metrics["loss"]))
        dt = time.perf_counter() - t0
    return batch * SEQ * steps / dt


def _measure_serving(cfg, *, n_requests: int = 128, prompt_len: int = 128,
                     gen: int = 32, slots: int = 64,
                     arrival_rate: float = 40.0,
                     params=None, adapter_factory=None,
                     prompt_mix: dict = None, mix_name: str = None,
                     ragged: bool = False,
                     prefill_chunk: int = 0,
                     spec: bool = False) -> dict:
    """Continuous-batching engine (paged KV cache), measured two ways
    (harness shape: the reference's serve microbenchmark,
    python/ray/serve/benchmarks/microbenchmark.py):

    * OPEN-LOOP: requests arrive at ``arrival_rate`` req/s (the
      serving-latency methodology — TTFT at an offered load, not after
      a burst drains a queue);
    * BURST: all requests at once — the max-throughput number.

    ``prompt_mix`` draws per-request prompt lengths from a weighted
    distribution (PROMPT_MIXES) instead of the fixed ``prompt_len``;
    ``ragged`` serves through the unified token-budget step
    (EngineConfig.ragged_batching) with ``prefill_chunk``-token prompt
    slices, so long prompts never head-of-line-block running decodes.
    """
    from ray_tpu.serve.llm_engine import (
        EngineConfig,
        LLMEngine,
        llama_paged_adapter,
    )

    if params is None:
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
    make_adapter = adapter_factory or llama_paged_adapter
    rng = np.random.default_rng(1)
    if prompt_mix is not None:
        lens = rng.choice(np.asarray(prompt_mix["lens"]), n_requests,
                          p=np.asarray(prompt_mix["weights"], np.float64)
                          / np.sum(prompt_mix["weights"]))
    else:
        lens = np.full(n_requests, prompt_len)
    max_seq = min(cfg.max_seq_len,
                  max(512, int(64 * np.ceil((lens.max() + gen + 1) / 64))))
    zipf = (prompt_mix or {}).get("zipf")
    leg_t0 = time.time()  # waterfall-attribution window for this leg
    eng = LLMEngine(
        params, make_adapter(cfg),
        EngineConfig(max_slots=slots, max_seq_len=max_seq,
                     decode_chunk=8,
                     max_new_tokens_default=gen, page_size=64,
                     ragged_batching=ragged,
                     prefill_chunk=prefill_chunk,
                     prefix_cache=bool(zipf) and ragged,
                     # Speculative legs self-draft (draft == target):
                     # acceptance is 1.0 by construction, so the leg
                     # isolates the MECHANICAL overhead/benefit of
                     # k-token verify rows, not draft-model quality.
                     spec_decode=spec and ragged),
    )
    if zipf is not None:
        # Zipfian multi-tenant prompts: rank-k tenant drawn with
        # p(k) ∝ 1/k^alpha shares a fixed page-aligned prefix;
        # private_frac of requests belong to one-off tenants (the
        # honest cold-prefill baseline inside the same run).  Suffixes
        # are always fresh, and make_prompts() is re-invoked per
        # ladder rung so a rung never replays the previous rung's
        # exact prompts as trivial full-prompt hits.
        tenants = int(zipf.get("tenants", 8))
        alpha = float(zipf.get("alpha", 1.1))
        shared_frac = float(zipf.get("shared_frac", 0.6))
        private_frac = float(zipf.get("private_frac", 0.25))
        pz = np.arange(1, tenants + 1, dtype=np.float64) ** -alpha
        pz /= pz.sum()
        tenant_prefix = [
            rng.integers(0, cfg.vocab_size,
                         int(64 * max(1, round(
                             int(max(prompt_mix["lens"])) * shared_frac
                             / 64)))).tolist()
            for _ in range(tenants)]

        def make_prompts():
            out = []
            for n in lens:
                n = int(n)
                if rng.random() < private_frac:
                    out.append(rng.integers(0, cfg.vocab_size,
                                            n).tolist())
                    continue
                pre = tenant_prefix[int(rng.choice(tenants, p=pz))]
                shared = min(len(pre) // 64 * 64, (n - 1) // 64 * 64)
                out.append(pre[:shared]
                           + rng.integers(0, cfg.vocab_size,
                                          n - shared).tolist())
            return out

        prompts = make_prompts()
    else:
        make_prompts = None
        prompts = [rng.integers(0, cfg.vocab_size, int(n)).tolist()
                   for n in lens]
    # TTFT-by-hit-depth accounting (zipf mixes): (hit_tokens,
    # prompt_tokens, ttft_s) per open-loop request, across all rungs.
    prefix_samples = []
    # Warm every compiled variant the run will hit off the clock:
    # prefill batch sizes k ∈ {1, 2, 4, 8} (open-loop trickle admits
    # small groups; burst admits full ones) and every ladder chunk.
    wi = 0
    for kgroup in (1, 2, 4, slots):
        warm = [eng.submit(prompts[(wi + j) % len(prompts)],
                           max_new_tokens=gen) for j in range(kgroup)]
        wi += kgroup
        for s in warm:
            s.result(timeout_s=600)

    def pct(sorted_vals, q):
        return round(
            sorted_vals[min(len(sorted_vals) - 1,
                            int(q * len(sorted_vals)))] * 1e3, 1)

    def open_loop_point(rate: float, n: int) -> dict:
        if make_prompts is not None:
            prompts[:] = make_prompts()  # fresh suffixes per rung
        t0 = time.perf_counter()
        streams = []
        for i in range(n):
            target = t0 + i / rate
            delay = target - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            streams.append(eng.submit(prompts[i % len(prompts)],
                                      max_new_tokens=gen,
                                      temperature=0.0))
        outs = [s.result(timeout_s=600) for s in streams]
        dt = time.perf_counter() - t0
        if zipf is not None:
            prefix_samples.extend(
                (s._req.prefix_hit, len(s._req.prompt), s._req.ttft_s)
                for s in streams)
        ttfts = sorted(s._req.ttft_s for s in streams
                       if s._req.ttft_s is not None)
        assert all(len(o) == gen for o in outs)
        # Steady-state served rate: the OLS slope of completion
        # timestamps vs completion index over the MIDDLE of the run
        # (first fifth = warmup ramp, last twentieth = the drain
        # burst, both trimmed).  Completions arrive in decode-chunk
        # BURSTS, so an endpoint-ratio estimator wobbles by a burst
        # width (enough to flap the knee); the regression slope over
        # the trimmed window averages the bursts out.  A system
        # keeping up completes at the arrival rate → ~1.0; a
        # saturated one at its ceiling μ → μ/rate.
        done = sorted(s._req.finished_at for s in streams)
        ts = np.asarray(done[max(1, n // 5):-max(1, n // 20)])
        idx = np.arange(len(ts))
        slope = float(np.polyfit(idx, ts, 1)[0]) if len(ts) > 2 else 1.0
        served_ss = 1.0 / max(slope, 1e-9)
        completion = min(1.0, served_ss / rate)
        return {
            "offered_req_s": rate,
            "req_per_s": round(n / dt, 2),
            "completion": round(completion, 3),
            # Token throughput from the OLS served rate, not n*gen/dt:
            # below the knee the run-wide ratio just echoes the PACING
            # rate (requests arrive slower than the engine could serve),
            # understating capacity at every sustainable point.
            "decode_tokens_per_s": round(served_ss * gen, 1),
            "ttft_p50_ms": pct(ttfts, 0.50),
            "ttft_p95_ms": pct(ttfts, 0.95),
        }

    # Arrival-rate LADDER: climb offered load until the system stops
    # completing ≥99% of it; the KNEE is the last sustainable point
    # and the headline TTFT is measured there, not past saturation.
    # A rung whose TTFT p95 blows past 10x its p50 hit a bimodal stall
    # (one-off compile, page thrash, preempted host) rather than a
    # smooth queueing regime: flag it ``stalled`` and retry once — the
    # flagged sample stays in the ladder for the record, the retry's
    # numbers stand.  BENCH_r05's 1.14B rung (p95 203x p50, completion
    # 0.116) is the motivating specimen — and it must NEVER be
    # promoted to "knee" just for being the only rung measured: a
    # ladder with no sustaining rung reports knee: null + saturated.
    ladder = []

    def probe(rate: float) -> dict:
        n = max(32, min(int(rate * 12), 192))
        point = open_loop_point(rate, n)
        if (point["ttft_p50_ms"] > 0
                and point["ttft_p95_ms"] > 10.0 * point["ttft_p50_ms"]):
            point["stalled"] = True
            ladder.append(point)
            point = open_loop_point(rate, n)
            point["retry_of_stalled"] = True
            if (point["ttft_p50_ms"] > 0
                    and point["ttft_p95_ms"]
                    > 10.0 * point["ttft_p50_ms"]):
                point["stalled"] = True  # reproduced: a real regime
        ladder.append(point)
        return point

    rate = arrival_rate / 4.0
    knee = None
    first_fail = None
    for _ in range(6):
        point = probe(rate)
        if point["completion"] >= 0.99:
            knee = point
            rate *= 1.5
        else:
            first_fail = point
            break
    # Refine the bracket between the last sustaining and the first
    # failing rung down to <=1.25x spacing (geometric bisection), so
    # the reported knee is within one fine rung of the true one.
    if knee is not None and first_fail is not None:
        lo = knee["offered_req_s"]
        hi = first_fail["offered_req_s"]
        while hi / lo > 1.25 and len(ladder) < 12:
            mid = (lo * hi) ** 0.5
            point = probe(mid)
            if point["completion"] >= 0.99:
                knee, lo = point, mid
            else:
                hi = mid
    saturated = knee is None  # not even the lowest rung sustained

    # Burst: everything at once — the throughput ceiling.
    t0 = time.perf_counter()
    streams_b = [eng.submit(p, max_new_tokens=gen, temperature=0.0)
                 for p in prompts]
    for s in streams_b:
        s.result(timeout_s=600)
    burst_dt = time.perf_counter() - t0
    eng_stats = eng.stats()
    # Migrated-vs-recomputed prefix cost (zipf mixes): ship this run's
    # hot cached prefixes to a cold engine over the kv_transfer int8
    # wire and time it, against the same run's MEASURED cold-prefill
    # cost (cold requests' TTFT per prompt token).  Needs the warm
    # engine alive, so it runs before shutdown.
    mig_probe = None
    if zipf is not None:
        try:
            mig_probe = _probe_prefix_migration(
                eng, cfg, params, make_adapter, max_seq)
        except Exception as e:
            mig_probe = {"error": repr(e)[:120]}
    eng.shutdown()
    # Headline open-loop numbers are AT THE KNEE (highest offered load
    # still completing ≥99%), so TTFT never conflates service with
    # queueing delay past saturation.  A saturated ladder (no rung
    # sustained) has NO honest headline: those fields go null and the
    # per-rung data lives in "ladder" — knee and saturated are
    # mutually exclusive by construction (scripts/bench_schema.py
    # enforces this on every record).
    head = knee if knee is not None else {
        "offered_req_s": None, "req_per_s": None,
        "decode_tokens_per_s": None, "ttft_p50_ms": None,
        "ttft_p95_ms": None}
    out = {
        "arrival_rate_req_s": head["offered_req_s"],
        "req_per_s": head["req_per_s"],
        "decode_tokens_per_s": head["decode_tokens_per_s"],
        "ttft_p50_ms": head["ttft_p50_ms"],
        "ttft_p95_ms": head["ttft_p95_ms"],
        "ladder": ladder,
        "knee_req_s": None if knee is None else knee["offered_req_s"],
        "saturated": saturated,
        "burst_req_per_s": round(n_requests / burst_dt, 2),
        "burst_decode_tokens_per_s": round(n_requests * gen / burst_dt, 1),
        "prompt_len": int(np.median(lens)),
        "gen": gen,
        "slots": slots,
        "batching": "ragged" if ragged else "interleaved",
        "kv": "int8" if getattr(cfg, "kv_int8", False) else "bf16",
        "decode_kernel": ("fused" if getattr(cfg, "fused_decode", False)
                          else "unfused"),
    }
    # Speculative-decoding stats (absent, not zero, when the engine
    # never completed a verify round — scripts/bench_schema.py
    # enforces the shape).  accepted_tokens_per_step counts the bonus
    # token, so a healthy leg sits above 1.0 accepted tokens per
    # target step.
    sp = eng_stats.get("spec")
    if spec and sp and sp.get("rounds"):
        out["spec"] = {
            "rounds": int(sp["rounds"]),
            "drafted_tokens": int(sp["drafted_tokens"]),
            "accepted_tokens": int(sp["accepted_tokens"]),
            "accept_ratio": (
                round(sp["accepted_tokens"] / sp["drafted_tokens"], 3)
                if sp["drafted_tokens"] else None),
            "accepted_tokens_per_step": round(
                (sp["accepted_tokens"] + sp["rounds"]) / sp["rounds"], 2),
            "cooldowns": int(sp.get("cooldowns", 0)),
            "k": int(sp["k"]),
            "draft": "self",
        }
    # Per-request waterfall aggregate over this leg's requests: mean
    # component seconds + control-plane share (absent, not zero, when
    # nothing was attributed — scripts/bench_schema.py validates).
    try:
        from ray_tpu.serve import latency_attribution

        dispatch_overhead = latency_attribution.aggregate(since=leg_t0)
    except Exception:
        dispatch_overhead = None
    if dispatch_overhead is not None:
        out["dispatch_overhead"] = dispatch_overhead
    if prompt_mix is not None:
        # The sampled distribution travels WITH the knee it produced:
        # a mixed-ladder TTFT is meaningless without knowing how long
        # the prompts actually were.
        out["prompt_mix"] = {
            "name": mix_name,
            "lens": [int(x) for x in prompt_mix["lens"]],
            "weights": [round(float(w), 4) for w in prompt_mix["weights"]],
            "sampled_p50": int(np.percentile(lens, 50)),
            "sampled_p95": int(np.percentile(lens, 95)),
            "sampled_max": int(lens.max()),
        }
        if zipf is not None:
            out["prompt_mix"]["zipf"] = dict(zipf)
    if zipf is not None:
        # Prefix-cache effectiveness over every open-loop request:
        # hit ratio, and TTFT split cold (hit = 0) vs deep-hit
        # (≥ 50% of the prompt served from cache) — the
        # TTFT-by-hit-depth comparison the cache is judged on.
        def _ms(vals, f):
            vals = [v for v in vals if v is not None]
            return None if not vals else round(float(f(vals)) * 1e3, 1)

        cold = [t for h, _p, t in prefix_samples if h == 0]
        deep = [t for h, p, t in prefix_samples
                if p > 0 and h >= 0.5 * p]
        tot_prompt = sum(p for _h, p, _t in prefix_samples)
        eng_prefix = eng_stats.get("prefix", {})
        out["prefix"] = {
            "requests": len(prefix_samples),
            "hit_ratio": (round(sum(1 for h, _p, _t in prefix_samples
                                    if h > 0)
                                / max(1, len(prefix_samples)), 3)),
            "hit_token_ratio": round(
                sum(h for h, _p, _t in prefix_samples)
                / max(1, tot_prompt), 3),
            "cold_requests": len(cold),
            "hit50_requests": len(deep),
            "ttft_mean_cold_ms": _ms(cold, np.mean),
            "ttft_mean_hit50_ms": _ms(deep, np.mean),
            "ttft_p50_cold_ms": _ms(cold, np.median),
            "ttft_p50_hit50_ms": _ms(deep, np.median),
            "cached_pages": int(eng_prefix.get("cached_pages", 0)),
            "evicted_pages": int(eng_prefix.get("evicted_pages", 0)),
        }
        if mig_probe is not None and "error" not in mig_probe:
            # Per-page costs: transfer side measured by the probe,
            # recompute side from the run's own cold requests (64 =
            # the engine's page_size above).  Null only when a side
            # measured nothing — no pages moved / no cold requests.
            cold_tok = sum(p for h, p, t in prefix_samples
                           if h == 0 and t is not None)
            cold_s = sum(t for h, p, t in prefix_samples
                         if h == 0 and t is not None)
            pages = mig_probe["migrated_pages"]
            mig_probe["migrate_s_per_page"] = (
                round(mig_probe["seconds"] / pages, 6) if pages
                else None)
            mig_probe["recompute_s_per_page"] = (
                round(cold_s / cold_tok * 64, 6) if cold_tok else None)
            m_pp = mig_probe["migrate_s_per_page"]
            r_pp = mig_probe["recompute_s_per_page"]
            mig_probe["migrate_vs_recompute"] = (
                round(r_pp / m_pp, 2) if m_pp and r_pp else None)
        if mig_probe is not None:
            out["prefix"]["migration"] = mig_probe
    return out


def _probe_prefix_migration(eng, cfg, params, make_adapter, max_seq):
    """Ship the warm engine's hot cached prefixes to a COLD engine over
    the kv_transfer int8 page wire (export_hot_prefixes -> ingest) and
    time it — the transfer half of the migrated-vs-recomputed prefix
    cost the zipf_chat record carries.  The timing includes the cold
    engine's one-time ingest compile, so the reported per-page cost is
    conservative (a steady-state pull is cheaper than this number)."""
    from ray_tpu.serve.llm_engine import EngineConfig, LLMEngine

    cold = LLMEngine(
        params, make_adapter(cfg),
        EngineConfig(max_slots=4, max_seq_len=max_seq, decode_chunk=8,
                     page_size=64, ragged_batching=True,
                     prefix_cache=True))
    try:
        t0 = time.perf_counter()
        transfers = eng.export_hot_prefixes(max_pages=512, mode="int8")
        pages = sum(cold.migration_ingest(t) for t in transfers)
        dt = time.perf_counter() - t0
    finally:
        cold.shutdown()
    return {"migrated_pages": int(pages),
            "wire_bytes": int(sum(int(t.get("wire_bytes", 0))
                                  for t in transfers)),
            "seconds": round(dt, 4)}


def _measure_serving_disagg(cfg, *, n_requests: int = 10, gen: int = 24,
                            lens=(512, 1024, 1536),
                            weights=(0.3, 0.5, 0.2),
                            arrival_rate: float = 2.0,
                            handoff_after_tokens: int = 2,
                            slots: int = 8,
                            params=None, adapter_factory=None) -> dict:
    """long_rag disaggregation on/off ablation, direct two-engine drive.

    OFF (unified): one engine serves the mix — long prefills and
    running decodes share the token-budget step, so a 1536-token
    prefill stretches every concurrent stream's inter-token latency.
    ON (disagg): a prefill engine serves the prompt plus the first
    ``handoff_after_tokens`` tokens, the finished pages migrate to a
    decode engine through the kv_transfer plane (lease -> int8 export
    -> ingest -> release, the same verbs the serve-path handoff uses),
    and decode resumes there against a prefix hit — the decode engine
    never runs a long prefill, which is the ITL separation this
    ablation measures.  TTFT is the prefill engine's (the client holds
    its first token before any page moves); a failed transfer falls
    back to serving the remainder on the prefill engine (the serve
    path's recompute fallback) and is counted in migration.failed.
    The serve-path handoff itself (router, MIGRATING ring state,
    SIGKILL fallback) is tier-1-tested in tests/test_disagg_serving.py.
    """
    import threading

    from ray_tpu.serve.llm_engine import (
        EngineConfig,
        LLMEngine,
        llama_paged_adapter,
    )

    if params is None:
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
    make_adapter = adapter_factory or llama_paged_adapter
    rng = np.random.default_rng(7)
    req_lens = rng.choice(np.asarray(lens), n_requests,
                          p=np.asarray(weights, np.float64)
                          / np.sum(weights))
    prompts = [rng.integers(0, cfg.vocab_size, int(n)).tolist()
               for n in req_lens]
    max_seq = min(cfg.max_seq_len,
                  max(256, int(64 * np.ceil((int(req_lens.max())
                                             + gen + 1) / 64))))

    def make_engine():
        return LLMEngine(
            params, make_adapter(cfg),
            EngineConfig(max_slots=slots, max_seq_len=max_seq,
                         decode_chunk=4, page_size=64,
                         max_new_tokens_default=gen,
                         ragged_batching=True, prefill_chunk=256,
                         prefix_cache=True))

    def pct_ms(vals, q):
        vals = sorted(v for v in vals if v is not None)
        if not vals:
            return None
        return round(vals[min(len(vals) - 1,
                              int(q * len(vals)))] * 1e3, 2)

    def leg_stats(ttfts, itls, decode_tokens, dt):
        return {"ttft_p50_ms": pct_ms(ttfts, 0.50),
                "ttft_p95_ms": pct_ms(ttfts, 0.95),
                "itl_p50_ms": pct_ms(itls, 0.50),
                "itl_p95_ms": pct_ms(itls, 0.95),
                "decode_tokens_per_s": round(decode_tokens / dt, 1)}

    # Off-the-clock warm prompt: NOT one of the timed prompts, so the
    # prefix cache never hands the unified leg a free hit.
    warm_prompt = rng.integers(0, cfg.vocab_size,
                               int(min(lens))).tolist()

    # --- OFF: unified engine -----------------------------------------
    leg_t0 = time.time()  # waterfall-attribution window for this leg
    uni = make_engine()
    try:
        uni.submit(warm_prompt, max_new_tokens=gen,
                   temperature=0.0).result(timeout_s=600)
        t0 = time.perf_counter()
        streams = []
        for i, p in enumerate(prompts):
            delay = t0 + i / arrival_rate - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            streams.append(uni.submit(p, max_new_tokens=gen,
                                      temperature=0.0))
        outs = [s.result(timeout_s=600) for s in streams]
        dt_u = time.perf_counter() - t0
        ttfts_u = [s._req.ttft_s for s in streams]
        itls_u = [(s._req.finished_at - s._req.first_token_at)
                  / (len(o) - 1)
                  for s, o in zip(streams, outs) if len(o) > 1]
        toks_u = sum(len(o) for o in outs)
    finally:
        uni.shutdown()

    # --- ON: prefill engine -> page migration -> decode engine -------
    pre = make_engine()
    dec = make_engine()
    mig_lock = threading.Lock()
    mig = {"pages": 0, "wire_bytes": 0, "seconds": 0.0, "failed": 0}
    results = [None] * n_requests

    def run_one(prompt):
        s = pre.submit(prompt, max_new_tokens=handoff_after_tokens,
                       temperature=0.0)
        first = s.result(timeout_s=600)
        ttft = s._req.ttft_s
        seq = list(prompt) + list(first)
        lease = None
        transfer = None
        moved = 0
        t1 = time.perf_counter()
        try:
            lease = pre.migration_lease(seq)
            if lease is not None:
                transfer = pre.migration_export(lease["lease_id"],
                                                mode="int8")
                moved = dec.migration_ingest(transfer)
        except Exception:
            moved = 0
        finally:
            if lease is not None:
                pre.migration_release(lease["lease_id"])
        dt_m = time.perf_counter() - t1
        if moved:
            with mig_lock:
                mig["pages"] += moved
                mig["wire_bytes"] += int(transfer.get("wire_bytes", 0))
                mig["seconds"] += dt_m
            eng2 = dec
        else:
            with mig_lock:
                mig["failed"] += 1
            eng2 = pre
        s2 = eng2.submit(seq,
                         max_new_tokens=gen - handoff_after_tokens,
                         temperature=0.0)
        rest = s2.result(timeout_s=600)
        gap = s2._req.first_token_at - s._req.finished_at
        itl = ((s2._req.finished_at - s2._req.first_token_at)
               / (len(rest) - 1)) if len(rest) > 1 else None
        return ttft, itl, len(first) + len(rest), gap

    try:
        run_one(warm_prompt)  # compiles prefill/transfer/resume paths
        mig.update(pages=0, wire_bytes=0, seconds=0.0, failed=0)

        def worker(i, p):
            results[i] = run_one(p)

        t0 = time.perf_counter()
        threads = []
        for i, p in enumerate(prompts):
            delay = t0 + i / arrival_rate - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            th = threading.Thread(target=worker, args=(i, p),
                                  daemon=True)
            th.start()
            threads.append(th)
        for th in threads:
            th.join(timeout=600)
        dt_d = time.perf_counter() - t0
    finally:
        pre.shutdown()
        dec.shutdown()

    done = [r for r in results if r is not None]
    unified = leg_stats(ttfts_u, itls_u, toks_u, dt_u)
    disagg = leg_stats([r[0] for r in done],
                       [r[1] for r in done], sum(r[2] for r in done),
                       dt_d)
    disagg["handoff_gap_p50_ms"] = pct_ms([r[3] for r in done], 0.50)
    disagg["migration"] = {"pages": int(mig["pages"]),
                           "wire_bytes": int(mig["wire_bytes"]),
                           "seconds": round(mig["seconds"], 4),
                           "failed": int(mig["failed"])}
    ratio = None
    if unified["itl_p95_ms"] and disagg["itl_p95_ms"]:
        ratio = round(unified["itl_p95_ms"] / disagg["itl_p95_ms"], 2)
    out = {
        "mix": {"name": "long_rag", "lens": [int(x) for x in lens],
                "weights": [round(float(w), 4) for w in weights]},
        "n_requests": n_requests,
        "gen": gen,
        "handoff_after_tokens": handoff_after_tokens,
        "transfer": "int8",
        "unified": unified,
        "disagg": disagg,
        "itl_p95_ratio": ratio,
    }
    try:
        from ray_tpu.serve import latency_attribution

        dispatch_overhead = latency_attribution.aggregate(since=leg_t0)
    except Exception:
        dispatch_overhead = None
    if dispatch_overhead is not None:
        out["dispatch_overhead"] = dispatch_overhead
    return out


def _measure_serving_adapters(cfg, *, n_adapters: int = 6,
                              pool_adapters: int = 4,
                              n_requests: int = 24, gen: int = 16,
                              prompt_len: int = 96,
                              zipf_alpha: float = 1.1,
                              arrival_rate: float = 8.0,
                              slots: int = 8, rank: int = 4,
                              params=None) -> dict:
    """zipf_adapters: multi-tenant LoRA multiplexing vs single-model.

    Requests draw their adapter id from a Zipf popularity curve over
    ``n_adapters`` tenants while the paged pool only holds
    ``pool_adapters`` of them — the head tenants stay resident (pool
    hits) and the tail churns through the refcount-0 LRU (misses +
    evictions), which is the steady state a multiplexed deployment
    runs in.  The single-model leg serves the SAME prompts through the
    same engine shape without LoRA; ``throughput_degradation`` =
    multiplexed tokens/s over single-model tokens/s, the price of the
    segmented gathered-einsum delta plus adapter load churn."""
    import dataclasses as _dc

    from ray_tpu.ops import segmented_lora as _sl
    from ray_tpu.serve.llm_engine import (
        EngineConfig,
        LLMEngine,
        llama_paged_adapter,
    )

    if params is None:
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(11)
    ranks = np.arange(1, n_adapters + 1, dtype=np.float64)
    pz = ranks ** -zipf_alpha
    pz /= pz.sum()
    draws = rng.choice(n_adapters, n_requests, p=pz)
    prompts = [rng.integers(0, cfg.vocab_size, prompt_len).tolist()
               for _ in range(n_requests)]
    max_seq = min(cfg.max_seq_len,
                  max(128, int(64 * np.ceil((prompt_len + gen + 1)
                                            / 64))))
    lora = _sl.LoRAConfig(rank=rank, alpha=2.0 * rank)
    page_elems = 8192
    pp = -(-_sl.adapter_elems(cfg, lora) // page_elems)

    def run_leg(model_cfg, ids):
        ecfg = EngineConfig(
            max_slots=slots, max_seq_len=max_seq, page_size=32,
            decode_chunk=4, ragged_batching=True, prefill_chunk=64,
            max_new_tokens_default=gen,
            adapter_pool_pages=(pool_adapters * pp if ids else 0),
            adapter_page_elems=page_elems)
        eng = LLMEngine(params, llama_paged_adapter(model_cfg), ecfg)
        try:
            # Warm compile off the clock (the LoRA program too).
            eng.submit(prompts[0][: prompt_len // 2],
                       max_new_tokens=gen, temperature=0.0,
                       adapter_id=(ids[0] if ids else "")
                       ).result(timeout_s=600)
            t0 = time.perf_counter()
            streams = []
            for i, p in enumerate(prompts):
                delay = t0 + i / arrival_rate - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                streams.append(eng.submit(
                    p, max_new_tokens=gen, temperature=0.0,
                    adapter_id=(ids[i] if ids else "")))
            outs = [s.result(timeout_s=600) for s in streams]
            dt = time.perf_counter() - t0
            ttfts = sorted(s._req.ttft_s for s in streams)
            leg = {
                "tokens_per_s": round(sum(len(o) for o in outs) / dt,
                                      1),
                "ttft_p50_ms": round(
                    ttfts[len(ttfts) // 2] * 1e3, 2),
                "ttft_p95_ms": round(
                    ttfts[min(len(ttfts) - 1,
                              int(0.95 * len(ttfts)))] * 1e3, 2),
            }
            pool = (eng.stats() or {}).get("adapters")
            if pool is not None:
                leg["pool"] = {k: pool[k] for k in
                               ("pool_pages", "resident", "hits",
                                "misses", "evictions", "hit_ratio")}
            return leg
        finally:
            eng.shutdown()

    single = run_leg(cfg, None)
    ids = [f"tenant-{d}" for d in draws]
    multi = run_leg(_dc.replace(cfg, lora=lora), ids)
    degr = None
    if single["tokens_per_s"]:
        degr = round(multi["tokens_per_s"] / single["tokens_per_s"], 3)
    return {
        "mix": {"name": "zipf_adapters", "n_adapters": n_adapters,
                "zipf_alpha": zipf_alpha,
                "pool_adapters": pool_adapters, "rank": rank},
        "n_requests": n_requests,
        "gen": gen,
        "single_model": single,
        "multi": multi,
        "throughput_degradation": degr,
    }


def _measure_serving_chaos(cfg, *, n_waves: int = 4, wave_size: int = 10,
                           gen: int = 10, prefix_len: int = 8,
                           tail_len: int = 4, max_replicas: int = 3,
                           slots: int = 4, decode_sleep_s: float = 0.02,
                           params=None) -> dict:
    """SLO-driven autoscaling under chaos: a full serve-plane run
    (controller, autoscaled LLMServer deployment, router) against
    ramped zipf_chat arrival with the replica killer active.

    The goodput leg, not a throughput leg: decode is throttled so
    requests live long enough for the reconciler's pressure signals
    (admission-queue age, ongoing count) to see the ramp.  Asserts by
    schema (bench_schema._check_chaos): the run must show at least one
    scale-up, at least one drain-based scale-down after the ramp ends,
    and at least one replica killed mid-traffic — otherwise the leg
    measured a static fleet on a sunny day.  One wave after the
    replica kill the CONTROLLER itself is hard-killed: the routers
    keep serving on their last broadcast while a replacement rebuilds
    from the checkpoint, and the record carries controller_kills plus
    the measured recovery_seconds (kill -> new controller actor
    answering status).  Sheds (admission-control
    refusals once the queue is over the SLO budget) are counted
    separately from goodput: nothing ran, so nothing failed."""
    import re as _re
    import threading

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.core import api
    from ray_tpu.core.exceptions import ShedError
    from ray_tpu.serve.llm_engine import (
        EngineConfig,
        LLMServer,
        llama_paged_adapter,
    )
    from ray_tpu.serve.controller import CONTROLLER_NAME
    from ray_tpu.util import metrics as _metrics
    from ray_tpu.utils.test_utils import ReplicaKiller, kill_actor_hard

    if params is None:
        params = llama.init_params(jax.random.PRNGKey(0), cfg)

    def slow_adapter_factory(c):
        # Paged + ragged (prefix_cache needs both); the throttle rides
        # the ragged step — a bare sleep would only fire at trace time.
        base = llama_paged_adapter(c)

        def slow_step(*a, **k):
            jax.debug.callback(lambda: time.sleep(decode_sleep_s),
                               ordered=True)
            return base.ragged_step(*a, **k)

        return dataclasses.replace(base, ragged_step=slow_step)

    def metric(family, tag_re=""):
        tot = 0.0
        pat = _re.compile(rf'^{family}{{[^}}]*{tag_re}[^}}]*}} (\S+)$')
        for line in _metrics.export_prometheus().splitlines():
            m = pat.match(line)
            if m:
                tot += float(m.group(1))
        return tot

    # zipf_chat arrival: a few hot shared prefixes (zipf popularity)
    # with unique tails, so prefix-affinity routing and the scale-up
    # warm start both have something to work with.
    rng = np.random.default_rng(11)
    prefixes = [rng.integers(1, cfg.vocab_size,
                             prefix_len).tolist() for _ in range(4)]
    zipf_w = np.array([1.0 / (i + 1) ** 1.1 for i in range(4)])
    zipf_w /= zipf_w.sum()

    def make_prompt():
        pre = prefixes[int(rng.choice(4, p=zipf_w))]
        return pre + rng.integers(1, cfg.vocab_size, tail_len).tolist()

    ray_tpu.init(num_cpus=16, ignore_reinit_error=True)
    serve.start()
    counts = {"completed": 0, "shed": 0, "failed": 0}
    lock = threading.Lock()
    max_groups = 0
    kills = 0
    controller_kills = 0
    recovery_seconds = None
    try:
        ups0 = metric("raytpu_serve_autoscale_decisions_total",
                      'direction="up"')
        downs0 = metric("raytpu_serve_autoscale_decisions_total",
                        'direction="down"')
        drains0 = metric("raytpu_serve_replica_drains_total")
        # Which signal fired each scale-up (decision `reason` tag):
        # predictive arrival_slope vs reactive queue_age/goodput/ongoing.
        reasons = ("arrival_slope", "queue_age", "goodput", "ongoing")
        ups_by_reason0 = {
            r: metric("raytpu_serve_autoscale_decisions_total",
                      f'direction="up"[^}}]*reason="{r}"')
            for r in reasons}
        app = serve.deployment(
            max_ongoing_requests=slots,
            autoscaling_config=dict(
                min_replicas=1, max_replicas=max_replicas,
                target_ongoing_requests=2.0, metrics_interval_s=0.05,
                look_back_period_s=0.5, upscale_delay_s=0.1,
                downscale_delay_s=0.3, target_queue_age_s=0.3,
                target_goodput=0.5,
                # Predictive arm: scale on arrival-rate slope before
                # the queue forms (serve/signals.ArrivalSignal).
                upscale_slope_threshold=1.0,
                arrival_half_life_s=0.5, arrival_slope_window_s=2.0),
        )(LLMServer).bind(
            cfg,
            EngineConfig(max_slots=slots,
                         max_seq_len=max(64, prefix_len + tail_len
                                         + gen + 16),
                         min_prefill_bucket=16, decode_chunk=1,
                         page_size=16, ragged_batching=True,
                         prefix_cache=True, shed_queue_age_s=3.0),
            lambda: params,
            adapter_factory=slow_adapter_factory,
        )
        handle = serve.run(app, name="chaos", route_prefix=None)
        shandle = handle.options(stream=True, max_retries=8)

        def run_one():
            try:
                shandle.remote({"tokens": make_prompt(),
                                "max_new_tokens": gen,
                                "temperature": 0.0}).result(timeout_s=300)
                with lock:
                    counts["completed"] += 1
            except ShedError:
                with lock:
                    counts["shed"] += 1
            except Exception:
                with lock:
                    counts["failed"] += 1

        # Warm the compiled paths off the clock.
        handle.remote({"tokens": make_prompt(), "max_new_tokens": 2,
                       "temperature": 0.0}).result(timeout_s=300)

        killer = ReplicaKiller(api.runtime(), seed=0)
        threads = []
        # Ramp: each wave doubles down on the queue before the last
        # one drains, so admission-queue age climbs and the reconciler
        # scales the group count up mid-traffic.
        for wave in range(n_waves):
            for _ in range(wave_size):
                th = threading.Thread(target=run_one, daemon=True)
                th.start()
                threads.append(th)
            time.sleep(0.4)
            max_groups = max(max_groups, int(metric(
                "raytpu_serve_autoscale_actual_groups")))
            # Chaos arm: once capacity scaled beyond one group, kill a
            # replica out from under the live waves (survivors absorb
            # the continuation replays).
            if kills == 0 and len(killer.victims()) >= 2:
                if killer.kill_one() is not None:
                    kills += 1
            # Control-plane chaos arm: one wave after the replica kill,
            # SIGKILL the controller itself mid-ramp.  The data plane
            # must keep serving on the last-known routing table while a
            # replacement controller rebuilds from its checkpoint;
            # recovery_seconds is kill -> a NEW controller actor (fresh
            # actor id, bumped epoch) answering status().
            elif kills >= 1 and controller_kills == 0:
                old = api.get_actor(CONTROLLER_NAME)
                t_kill = time.monotonic()
                kill_actor_hard(api.runtime(), old._actor_id)
                controller_kills += 1
                deadline_ctl = time.monotonic() + 60
                while time.monotonic() < deadline_ctl:
                    try:
                        fresh = serve._get_or_create_controller()
                        if fresh._actor_id != old._actor_id:
                            api.get(fresh.status.remote(), timeout=5.0)
                            recovery_seconds = round(
                                time.monotonic() - t_kill, 4)
                            break
                    except Exception:
                        pass
                    time.sleep(0.05)
        for th in threads:
            th.join(timeout=300)
        # Ramp over: wait for the policy to drain the extra groups
        # back down (downscale_delay + drain settle).
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            max_groups = max(max_groups, int(metric(
                "raytpu_serve_autoscale_actual_groups")))
            if (metric("raytpu_serve_autoscale_decisions_total",
                       'direction="down"') > downs0
                    and metric("raytpu_serve_autoscale_actual_groups")
                    <= 1):
                break
            time.sleep(0.1)
        ups = metric("raytpu_serve_autoscale_decisions_total",
                     'direction="up"') - ups0
        downs = metric("raytpu_serve_autoscale_decisions_total",
                       'direction="down"') - downs0
        drains = metric("raytpu_serve_replica_drains_total") - drains0
        # Absent-not-zero: only reasons that actually fired appear, so
        # the schema can tell "predictive arm never ran" from "ran and
        # scaled zero times" (bench_schema._check_autoscale_signals).
        scale_up_reasons = {}
        for r in reasons:
            n = int(metric("raytpu_serve_autoscale_decisions_total",
                           f'direction="up"[^}}]*reason="{r}"')
                    - ups_by_reason0[r])
            if n >= 1:
                scale_up_reasons[r] = n
        # Post-ramp invariant audit: kills + continuation replays +
        # scale-down drains are exactly the paths that leak KV pages or
        # adapter borrows, and a leg that leaked would still report
        # healthy goodput — the doctor's full partition walk is the
        # difference between "survived" and "survived intact"
        # (bench_schema._check_doctor requires violations == 0).
        from ray_tpu.util import state as _state

        t_doc = time.monotonic()
        doc = _state.doctor_report(deep=True)
        doctor = {
            "checks_run": int(doc.get("checks_run", 0)),
            "violations": int(doc.get("violations", 0)),
            "audit_seconds": round(time.monotonic() - t_doc, 4),
        }
    finally:
        serve.shutdown()
        ray_tpu.shutdown()
    offered = n_waves * wave_size
    return {
        "mix": "zipf_chat",
        "offered": offered,
        "completed": counts["completed"],
        "shed": counts["shed"],
        "failed": counts["failed"],
        "shed_fraction": round(counts["shed"] / offered, 4),
        "goodput_ratio": round(
            counts["completed"] / max(1, offered - counts["shed"]), 4),
        "scale_ups": int(ups),
        "scale_up_reasons": scale_up_reasons,
        "scale_downs": int(downs),
        "drain_retirements": int(drains),
        "kills": kills,
        "controller_kills": controller_kills,
        "recovery_seconds": recovery_seconds,
        "max_groups": max_groups,
        "max_replicas": max_replicas,
        "gen": gen,
        "doctor": doctor,
    }


def _measure_serving_mixed(cfg, *, n_requests: int = 48,
                           gen: int = 32, slots: int = 32,
                           arrival_rate: float = 8.0,
                           ragged: bool = True,
                           params=None, adapter_factory=None) -> dict:
    """The mixed-length ladder: one full knee ladder per PROMPT_MIX,
    served ragged (token-budget step, 256-token prefill slices) so the
    per-mix knees are comparable — the acceptance bar is that TTFT p95
    at the knee holds as the mix shifts from short_chat to long_rag.
    Ragged mixes serve speculatively (self-draft), report per-mix
    acceptance, and carry a burst-only spec-on/off ablation."""
    if params is None:
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
    out = {"batching": "ragged" if ragged else "interleaved",
           "mixes": {}}
    from ray_tpu.serve.llm_engine import llama_paged_adapter

    make_adapter = adapter_factory or llama_paged_adapter
    for name, mix in PROMPT_MIXES.items():
        try:
            leg = _measure_serving(
                cfg, n_requests=n_requests, gen=gen, slots=slots,
                arrival_rate=arrival_rate, params=params,
                adapter_factory=adapter_factory, prompt_mix=mix,
                mix_name=name, ragged=ragged,
                prefill_chunk=256 if ragged else 0,
                spec=ragged)
            out["mixes"][name] = leg
        except Exception as e:  # one collapsed mix must not eat the rest
            out["mixes"][name] = {"error": repr(e)[:120]}
            continue
        if "spec" not in leg:
            continue  # leg never speculated → no ablation (absent, not zero)
        try:
            leg["spec_ablation"] = _probe_spec_ablation(
                cfg, params, make_adapter, mix, gen=gen)
        except Exception as e:
            leg["spec_ablation"] = {"error": repr(e)[:120]}
    return out


def _probe_spec_ablation(cfg, params, make_adapter, mix, *,
                         n: int = 24, gen: int = 32,
                         slots: int = 16) -> dict:
    """Burst-only spec-on/off A/B on IDENTICAL prompts: the same mix,
    same seed, same engine shape, toggling only EngineConfig.spec_decode
    — so the delta is the verify-row machinery itself, not workload
    noise.  Burst (not open-loop) because the ablation question is
    decode-ceiling, and a full second knee ladder per mix would double
    the leg's wall clock for no extra signal."""
    from ray_tpu.serve.llm_engine import EngineConfig, LLMEngine

    rng = np.random.default_rng(5)
    lens = rng.choice(np.asarray(mix["lens"]), n,
                      p=np.asarray(mix["weights"], np.float64)
                      / np.sum(mix["weights"]))
    prompts = [rng.integers(0, cfg.vocab_size, int(L)).tolist()
               for L in lens]
    max_seq = min(cfg.max_seq_len,
                  max(512, int(64 * np.ceil((lens.max() + gen + 1) / 64))))
    out = {}
    for label, spec in (("on", True), ("off", False)):
        eng = LLMEngine(
            params, make_adapter(cfg),
            EngineConfig(max_slots=slots, max_seq_len=max_seq,
                         decode_chunk=8, max_new_tokens_default=gen,
                         page_size=64, ragged_batching=True,
                         prefill_chunk=256, spec_decode=spec))
        # Warm the compiled variants off the clock.
        eng.submit(prompts[0], max_new_tokens=gen,
                   temperature=0.0).result(timeout_s=600)
        t0 = time.perf_counter()
        streams = [eng.submit(p, max_new_tokens=gen, temperature=0.0)
                   for p in prompts]
        for s in streams:
            s.result(timeout_s=600)
        dt = time.perf_counter() - t0
        sp = eng.stats().get("spec")
        eng.shutdown()
        leg = {"decode_tokens_per_s": round(n * gen / dt, 1)}
        if spec and sp and sp.get("rounds"):
            leg["accept_ratio"] = (
                round(sp["accepted_tokens"] / sp["drafted_tokens"], 3)
                if sp["drafted_tokens"] else None)
            leg["accepted_tokens_per_step"] = round(
                (sp["accepted_tokens"] + sp["rounds"]) / sp["rounds"], 2)
        out[label] = leg
    off_tps = out["off"]["decode_tokens_per_s"]
    out["speedup"] = (round(out["on"]["decode_tokens_per_s"] / off_tps, 2)
                      if off_tps else None)
    return out


def _measure_8b_train(peak_flops: float) -> dict:
    """The MEASURED full-8B AdamW rung (no extrapolation, ever): all 32
    layers, 128k vocab, bf16 master + int8 Adam states ZeRO-sharded
    over the data axes (train/zero.py), gradient-accumulation
    microbatching so activations fit.  On hardware without enough
    aggregate HBM the rung reports a LOUD structured error with the
    memory math — never a scaled number."""
    from ray_tpu.train import TrainerConfig, adamw8bit
    from ray_tpu.train import zero as zero_mod

    devs = jax.devices()
    n = len(devs)
    cfg8t = llama.LlamaConfig(
        vocab_size=128_256, dim=4096, n_layers=32, n_heads=32,
        n_kv_heads=8, mlp_dim=14336, max_seq_len=SEQ,
        param_dtype=jnp.bfloat16, remat_policy="full", loss_chunk=512,
    )
    n_params = cfg8t.num_params()
    try:
        hbm = (devs[0].memory_stats() or {}).get("bytes_limit")
    except Exception:
        hbm = None
    if not hbm:
        hbm = 16 * 2**30  # v5e-class floor when the backend won't say
    # Per-chip plan, everything 1/n-sharded (params+grads over fsdp,
    # int8 states over the zero axes): 2 B/param params + 2 B/param
    # grad accumulator + ~2.1 B/param int8 states, plus ~2 GiB of
    # transients (gathered layer weights, remat activations, CE chunk).
    overhead = 2 * 2**30
    need = int(6.1 * n_params / n) + overhead
    if need > 0.92 * hbm:
        per_chip_ok = 0.92 * hbm - overhead
        min_chips = int(np.ceil(6.1 * n_params / max(per_chip_ok, 1)))
        return {
            "error": (f"full-8B AdamW needs ~{need / 2**30:.1f} GiB/chip "
                      f"on {n} chip(s) of {hbm / 2**30:.0f} GiB HBM; "
                      f"ZeRO-sharded it fits from {min_chips} chips"),
            "zero_sharding": True,
            "dp_shards": n,
            "est_bytes_per_chip": need,
            "hbm_bytes": int(hbm),
            "min_chips": min_chips,
        }
    grad_accum = 4
    batch = grad_accum * n
    extras: dict = {}
    tps = _measure(
        cfg8t, devs, steps=3, batch=batch,
        optimizer=adamw8bit(1e-4, warmup_steps=10, shard_update=True),
        trainer_config=TrainerConfig(zero_sharding=True,
                                     grad_accum=grad_accum),
        extras=extras,
    )
    trainer = extras["trainer"]
    bytes_ = zero_mod.opt_state_bytes(trainer.state.opt_state)
    ds = zero_mod.dp_shards(trainer.mesh)
    tps_chip = tps / n
    hbm_peak = None
    try:
        peaks = [(d.memory_stats() or {}).get("peak_bytes_in_use")
                 for d in devs]
        peaks = [p for p in peaks if p]
        hbm_peak = max(peaks) if peaks else None
    except Exception:
        pass
    return {
        "params_b": round(n_params / 1e9, 2),
        "measured": True,
        "tokens_per_sec_per_chip": round(tps_chip, 1),
        "mfu": round(tps_chip * 6 * n_params / peak_flops, 4),
        "zero_sharding": True,
        "dp_shards": ds,
        "grad_accum": grad_accum,
        "batch": batch,
        "seq": SEQ,
        "optimizer": "adamw8bit (int8 states, ZeRO-sharded)",
        "opt_state_bytes_per_param": round(
            bytes_["per_device"] / n_params, 4),
        "opt_state_bytes_per_device": bytes_["per_device"],
        "hbm_peak_gb": (round(hbm_peak / 2**30, 2)
                        if hbm_peak else None),
    }


def _measure_8b(peak_flops: float) -> dict:
    """North-star #3: the 8B story.

    * SERVING (measured): int8 weight-only quantized 8B (≈8.3 GB)
      fits 16 GB HBM next to a paged bf16 KV cache; decode tok/s and
      TTFT measured through the real engine.
    * TRAIN (measured): full 32-layer AdamW with int8 Adam states
      ZeRO-sharded over the data axes (_measure_8b_train) — the rung
      that replaced the retired depth-truncated extrapolation; when
      the hardware can't hold it, the record says so in an error block
      with the memory math instead of scaling a smaller measurement.
    """
    from ray_tpu.models import quant

    # int8 KV pages (per-page scales): the bf16 pool at 24 slots was
    # 3.2 GB; int8 at 48 slots × 4 pages is 0.4 GB — double the slots
    # AND less HBM, with live-page decode reads halved.
    # fused_decode: the per-layer megakernel (ops/fused_decode.py)
    # collapses each layer's decode op graph into one Pallas program —
    # the per-op dispatch latency it removes is what held 8B decode at
    # 56% of the weight-read roofline in BENCH_r05.
    cfg8 = llama.LlamaConfig(
        vocab_size=128_256, dim=4096, n_layers=32, n_heads=32,
        n_kv_heads=8, mlp_dim=14336, max_seq_len=256, kv_int8=True,
        fused_decode=True,
    )
    out: dict = {"params_b": round(cfg8.num_params() / 1e9, 2)}

    qparams = quant.init_quantized_llama(jax.random.PRNGKey(0), cfg8)
    # Fused qkv + gate/up: 5 projection matmuls → 2 per layer (decode
    # is per-op latency-bound on top of the weight reads).
    qparams = quant.fuse_for_decode(qparams, cfg8)
    jax.block_until_ready(qparams)
    out["int8_weight_gb"] = round(quant.quantized_bytes(qparams) / 2**30, 2)
    serving = _measure_serving(
        cfg8, n_requests=96, prompt_len=128, gen=32, slots=48,
        arrival_rate=4.0, params=qparams,
        adapter_factory=quant.llama_paged_adapter_quant,
    )
    out["serving_int8"] = serving
    del qparams, serving

    # Full-8B measured train rung (ZeRO-sharded int8 Adam states).
    try:
        out["train"] = _measure_8b_train(peak_flops)
    except Exception as e:
        out["train"] = {"error": repr(e).replace(": ", ":")
                        .replace(", ", ",")[:160],
                        "zero_sharding": True}
    return out


def _measure_serving_multihost(cfg, *, shard_counts=(1, 2, 4),
                               n_requests: int = 16, gen: int = 16,
                               prompt_len: int = 32,
                               params=None) -> dict:
    """Multi-host tensor-parallel serving ladder: one engine per rung,
    weights sharded over a ``dcn_tp x tp`` serving mesh (shard count =
    hosts in the shard group; on CPU, contiguous virtual-device groups
    stand in for the host boundary).  Every multi-shard rung runs the
    DCN ablation — exact bf16-fallback collectives vs the int8
    quantized allreduce (EQuARX-style per-chunk scales) — recording
    greedy burst throughput plus the same per-decode-step
    bytes-on-wire accounting the serve telemetry counters use, so the
    record shows the >= 3x DCN reduction directly."""
    from ray_tpu.parallel.collectives import allreduce_wire_bytes
    from ray_tpu.parallel.mesh import create_serving_mesh
    from ray_tpu.serve.llm_engine import (
        EngineConfig,
        LLMEngine,
        llama_paged_adapter,
    )

    devs = jax.devices()
    if params is None:
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(2)
    prompts = [rng.integers(0, cfg.vocab_size, prompt_len).tolist()
               for _ in range(n_requests)]
    chunk = 32  # divides dim -> no pad waste in the quantized wire
    ladder = []
    for shards in shard_counts:
        # KV pools shard along heads over the COMBINED (dcn_tp, tp)
        # axis, so the whole group size must divide n_kv_heads.
        tp = max(1, min(len(devs) // shards, cfg.n_kv_heads // shards))
        if shards * tp > len(devs) or cfg.n_kv_heads % (shards * tp):
            continue
        for mode in (("bf16",) if shards == 1 else ("bf16", "int8")):
            cfg2 = dataclasses.replace(
                cfg, tensor_parallel=True,
                dcn_quantized_allreduce=(mode == "int8"),
                dcn_allreduce_chunk=chunk)
            eng = LLMEngine(
                params, llama_paged_adapter(cfg2),
                EngineConfig(max_slots=n_requests,
                             max_seq_len=max(128, prompt_len + gen + 16),
                             decode_chunk=8, page_size=16,
                             max_new_tokens_default=gen),
                mesh=create_serving_mesh(shards, tp),
            )
            try:
                # Warm the compiled variants off the clock.
                eng.submit(prompts[0],
                           max_new_tokens=gen).result(timeout_s=600)
                t0 = time.perf_counter()
                streams = [eng.submit(p, max_new_tokens=gen,
                                      temperature=0.0)
                           for p in prompts]
                n_tokens = sum(
                    len(s.result(timeout_s=600)) for s in streams)
                dt = time.perf_counter() - t0
                coll = (eng._coll_bytes_fn(1) if eng._coll_bytes_fn
                        else {"ici": 0, "dcn": 0})
            finally:
                eng.shutdown()
            fp32_dcn = 2 * cfg.n_layers * allreduce_wire_bytes(
                cfg.dim, axis_size=shards, quantized=False)
            ladder.append({
                "shards": shards,
                "tp": tp,
                "dcn_collective": mode,
                "toks_per_s": round(n_tokens / dt, 1),
                "ici_bytes_per_step": int(coll["ici"]),
                "dcn_bytes_per_step": int(coll["dcn"]),
                "dcn_bytes_ratio_vs_fp32": (
                    round(fp32_dcn / coll["dcn"], 2)
                    if coll["dcn"] else None),
            })
    return {"ladder": ladder}


def _measure_ssd(B=4, S=4096, H=8, P=64, N=128, chunk=128,
                 iters=64) -> dict:
    """Fused Pallas SSD kernel vs the einsum+associative_scan path
    (models/mamba2.ssd_chunked), same inputs, forward pass.

    DEVICE time, not wall time: all ``iters`` iterations chain inside
    ONE jitted ``lax.scan`` (each feeds a damped mix of its output
    back into the next input, so XLA can neither hoist nor DCE the
    body), which amortizes the tunnel's per-dispatch overhead to
    <1/iters of the measurement — host contention can no longer mask
    kernel differences (round-4 verdict weak #2)."""
    from ray_tpu.models.mamba2 import ssd_chunked
    from ray_tpu.ops.mamba_ssd import ssd_pallas

    key = jax.random.key(0)
    k1, k2, k3, k4 = jax.random.split(key, 4)
    x = jax.random.normal(k1, (B, S, H, P), jnp.float32)
    la = -jax.nn.softplus(jax.random.normal(k2, (B, S, H)))
    Bm = jax.random.normal(k3, (B, S, N), jnp.float32) * 0.3
    Cm = jax.random.normal(k4, (B, S, N), jnp.float32) * 0.3

    def compiled(fn):
        def many(x0):
            def body(carry, _):
                out = fn(carry, la, Bm, Cm)
                # Damped feedback: a REAL data dependency between
                # iterations at the same input statistics.
                return 0.9 * carry + 0.1 * out, ()

            final, _ = jax.lax.scan(body, x0, None, length=iters)
            return final

        f = jax.jit(many)
        out = f(x)
        float(jax.device_get(out[0, 0, 0, 0]))  # compile + fence
        return f

    def timed_once(f):
        t0 = time.perf_counter()
        out = f(x)
        float(jax.device_get(out[0, 0, 0, 0]))
        return (time.perf_counter() - t0) / iters

    f_scan = compiled(lambda *a: ssd_chunked(*a, chunk=chunk))
    f_pallas = compiled(lambda *a: ssd_pallas(*a, chunk))
    # The tunneled chip's effective speed drifts on minute timescales
    # (common mode: both paths swing together).  INTERLEAVE the two
    # paths' timed calls and take per-path medians so the ratio
    # samples the same windows — a ratio from two disjoint windows can
    # be off 40% in either direction.
    reps_s, reps_p = [], []
    for _ in range(5):
        reps_s.append(timed_once(f_scan))
        reps_p.append(timed_once(f_pallas))
    t_scan = float(np.median(reps_s))
    t_pallas = float(np.median(reps_p))
    # On-chip correctness ride-along: interpret-mode CPU tests can't
    # catch a hardware-only Mosaic miscompile of the flattened layout.
    out_scan = jax.jit(lambda *a: ssd_chunked(*a, chunk=chunk))(
        x, la, Bm, Cm)
    out_pallas = jax.jit(lambda *a: ssd_pallas(*a, chunk))(
        x, la, Bm, Cm)
    max_diff = float(jnp.max(jnp.abs(out_scan - out_pallas)))
    tok_s = B * S / t_pallas
    return {
        "shape": f"B{B} S{S} H{H} P{P} N{N} chunk{chunk}",
        "assoc_scan_ms": round(t_scan * 1e3, 2),
        "pallas_ms": round(t_pallas * 1e3, 2),
        "speedup": round(t_scan / t_pallas, 2),
        "pallas_tokens_per_s": round(tok_s, 0),
        "max_abs_diff_vs_reference": max_diff,
        "timing": "device (iters chained in one jitted scan)",
    }


def main():
    devices = jax.devices()
    on_tpu = devices[0].platform != "cpu"
    steps = 10 if on_tpu else 2
    cfg = BENCH_CFG if on_tpu else dataclasses.replace(
        BENCH_CFG, dim=256, n_layers=4, n_heads=8, n_kv_heads=4, mlp_dim=1024
    )

    tps = _measure(cfg, devices, steps=steps)
    # Baseline: same step in float32 — the throughput of a port that
    # ignores the MXU's bf16 preference.  (f32 *without* remat, the truly
    # naive variant, OOMs outright at this size: 34 GB of attention probs.)
    baseline_cfg = dataclasses.replace(cfg, dtype=jax.numpy.float32,
                                       remat_policy="full")
    try:
        baseline_tps = _measure(baseline_cfg, devices, steps=max(2, steps // 3))
    except Exception:
        baseline_tps = float("nan")

    n_chips = len(devices)
    tps_chip = tps / n_chips
    from ray_tpu.parallel.mesh import detect_topology

    gen = detect_topology().generation
    peak = PEAK_FLOPS.get(gen, 1e12)
    flops_per_token = 6 * cfg.num_params()
    mfu = tps_chip * flops_per_token / peak

    extra = {
        "chips": n_chips,
        "platform": gen,
        "mfu": round(mfu, 4),
        "batch": BATCH,
        "seq": SEQ,
        "params_m": round(cfg.num_params() / 1e6, 1),
    }

    if on_tpu:
        # North star #1: the largest single-chip config (≥1B params).
        try:
            cfg_1b = BENCH_1B_CFG
            tps_1b = _measure(cfg_1b, devices, steps=4) / n_chips
            extra["llama_1b"] = {
                "params_m": round(cfg_1b.num_params() / 1e6, 1),
                "tokens_per_sec_per_chip": round(tps_1b, 1),
                "mfu": round(tps_1b * 6 * cfg_1b.num_params() / peak, 4),
            }
        except Exception as e:
            extra["llama_1b"] = {"error": repr(e)[:120]}
        # The MEASURED multi-billion point: 2.24B end-to-end on one
        # chip via int8 Adam states (no extrapolation).
        try:
            from ray_tpu.train import adamw8bit

            cfg_2b = BENCH_2B_CFG
            tps_2b = _measure(
                cfg_2b, devices, steps=3, batch=4,
                optimizer=adamw8bit(1e-4, warmup_steps=10),
            ) / n_chips
            extra["llama_2b"] = {
                "params_b": round(cfg_2b.num_params() / 1e9, 2),
                "tokens_per_sec_per_chip": round(tps_2b, 1),
                "mfu": round(tps_2b * 6 * cfg_2b.num_params() / peak, 4),
                "optimizer": "adamw8bit (int8 block-quantized m,v)",
            }
        except Exception as e:
            extra["llama_2b"] = {"error": repr(e)[:120]}
        # North star #2: serving req/s + TTFT (continuous batching),
        # open-loop at an offered load + burst ceiling — for BOTH the
        # 319M and the 1.14B configs.
        try:
            extra["serving"] = _measure_serving(
                dataclasses.replace(cfg, max_seq_len=512))
        except Exception as e:
            extra["serving"] = {"error": repr(e)[:120]}
        try:
            extra["serving_1b"] = _measure_serving(
                dataclasses.replace(BENCH_1B_CFG, max_seq_len=512),
                n_requests=64, slots=32, arrival_rate=12.0)
        except Exception as e:
            extra["serving_1b"] = {"error": repr(e)[:120]}
        # The MIXED-length ladders (short-chat / long-RAG / bursty),
        # served through the ragged token-budget step: the knee under
        # realistic traffic, where the old two-program engine's TTFT
        # p95 exploded as soon as long prompts entered the mix.
        try:
            extra["serving_mixed"] = _measure_serving_mixed(
                dataclasses.replace(cfg, max_seq_len=2048),
                n_requests=64, slots=48, arrival_rate=16.0)
        except Exception as e:
            extra["serving_mixed"] = {"error": repr(e)[:120]}
        try:
            extra["serving_1b_mixed"] = _measure_serving_mixed(
                dataclasses.replace(BENCH_1B_CFG, max_seq_len=2048),
                n_requests=48, slots=32, arrival_rate=6.0)
        except Exception as e:
            extra["serving_1b_mixed"] = {"error": repr(e)[:120]}
        # BASELINE.json config-matrix: Pallas SSD kernel vs the
        # associative_scan/einsum path, measured on-chip.  Runs BEFORE
        # the 8B leg: after 8+ GB of weights churn through HBM the
        # chip measures both paths slower and noisier (observed 1.21x
        # post-8B vs 1.60x on a fresh chip).
        try:
            extra["mamba_ssd"] = _measure_ssd()
        except Exception as e:
            extra["mamba_ssd"] = {"error": repr(e)[:200]}
        # North star #3: the 8B artifact — int8 serving (measured) +
        # per-layer train extrapolation (BASELINE.md north-star row).
        try:
            extra["llama_8b"] = _measure_8b(peak)
        except Exception as e:
            extra["llama_8b"] = {"error": repr(e)[:200]}

    # Multi-host serving ladder: shard-group replicas on a hybrid
    # dcn_tp x tp mesh, quantized-vs-exact DCN ablation with
    # bytes-on-wire in the record.  Runs on CPU too (virtual devices
    # emulate the host groups), so every record carries the ladder.
    try:
        extra["serving_multihost"] = _measure_serving_multihost(
            dataclasses.replace(cfg, max_seq_len=512))
    except Exception as e:
        # No ", "/": " — the final stdout line must stay compact.
        extra["serving_multihost"] = {
            "error": repr(e).replace(": ", ":").replace(", ", ",")[:120]}

    # Disaggregated prefill/decode ablation on the long-RAG mix:
    # unified vs prefill -> kv_transfer -> decode, direct two-engine
    # drive (the serve-path handoff is tier-1-tested).  Runs on CPU
    # too with scaled prompt lengths, so every record carries it.
    try:
        extra["serving_disagg"] = _measure_serving_disagg(
            dataclasses.replace(cfg, max_seq_len=2048),
            **({} if on_tpu else
               {"lens": (96, 160, 224), "n_requests": 8, "gen": 16,
                "arrival_rate": 4.0}))
    except Exception as e:
        extra["serving_disagg"] = {
            "error": repr(e).replace(": ", ":").replace(", ", ",")[:120]}

    # Multi-tenant LoRA multiplexing: Zipf adapter popularity through
    # the paged adapter pool vs the same traffic single-model — pool
    # hit ratio and the segmented-matmul throughput price.  Runs on
    # CPU too with scaled counts, so every record carries it.
    try:
        extra["serving_adapters"] = _measure_serving_adapters(
            dataclasses.replace(cfg, max_seq_len=512),
            **({} if on_tpu else
               {"n_adapters": 5, "pool_adapters": 3, "n_requests": 12,
                "gen": 8, "prompt_len": 48, "arrival_rate": 6.0,
                "slots": 4, "rank": 2}))
    except Exception as e:
        extra["serving_adapters"] = {
            "error": repr(e).replace(": ", ":").replace(", ", ",")[:120]}

    # SLO-driven autoscaling chaos: full serve-plane run (controller +
    # autoscaled deployment + replica killer) under ramped zipf_chat
    # arrival — goodput ratio, shed fraction, scale events, kills
    # survived.  Runs on CPU too (control-plane behavior, not model
    # throughput), so every record carries it.
    try:
        chaos_cfg = (dataclasses.replace(cfg, max_seq_len=128) if on_tpu
                     else llama.LlamaConfig(
                         vocab_size=128, dim=32, n_layers=2, n_heads=4,
                         n_kv_heads=2, mlp_dim=64, max_seq_len=128,
                         remat=False))
        extra["serving_chaos"] = _measure_serving_chaos(
            chaos_cfg,
            **({} if on_tpu else {"n_waves": 3, "wave_size": 8}))
    except Exception as e:
        extra["serving_chaos"] = {
            "error": repr(e).replace(": ", ":").replace(", ", ",")[:120]}

    result = {
        "metric": f"llama_{cfg.num_params()/1e6:.0f}M_train_tokens_per_sec_per_chip",
        "value": round(tps_chip, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(tps / baseline_tps, 3) if baseline_tps == baseline_tps else None,
        "extra": extra,
    }
    # The record survives two independent ways: BENCH_OUT.json on disk
    # AND the final stdout line.  The driver wrapper parses that LAST
    # line into BENCH_r0N.json's ``parsed`` — BENCH_r05 shipped
    # parsed:null because its bounded stdout tail cut the line
    # mid-object.  So the line is COMPACT (no separator padding; ~25%
    # smaller, and the mixed ladders grow the record further), printed
    # last, and flushed; scripts/gen_perf_tables.py can still recover
    # the last complete JSON line from a wrapper, and the file copy
    # makes even that unnecessary when the filesystem comes home.
    blob = json.dumps(result, separators=(",", ":"))
    with open("BENCH_OUT.json", "w") as f:
        f.write(blob + "\n")
    print(blob, flush=True)


if __name__ == "__main__":
    main()
