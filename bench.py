"""Headline benchmark: Llama train-step throughput on the local chip(s).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tokens/sec/chip", "vs_baseline": N}

``value`` is tokens/sec/chip of the full jitted train step (fwd+bwd+Adam)
on a ~350M-param Llama config sized for a single v5e chip.

``vs_baseline`` compares against a deliberately un-TPU-optimized variant
of the same step — float32 compute, no rematerialization — i.e. the
throughput a straight port that ignores MXU dtype and HBM management
would get.  (The reference publishes no absolute tokens/sec itself; see
BASELINE.md.)
"""

from __future__ import annotations

import dataclasses
import json
import time

import jax
import numpy as np

from ray_tpu.models import llama
from ray_tpu.parallel import MeshSpec
from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig, default_optimizer

BATCH = 8
SEQ = 2048

BENCH_CFG = llama.LlamaConfig(
    vocab_size=32_768,
    dim=1024,
    n_layers=16,
    n_heads=16,
    n_kv_heads=8,
    mlp_dim=4096,
    max_seq_len=SEQ,
)

# bf16 peak per chip, for MFU reporting
PEAK_FLOPS = {
    "v5e": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6e": 918e12,
    "cpu": 1e12,  # nominal; MFU is meaningless on CPU
}


def _make_trainer(cfg, devices):
    return JaxTrainer(
        init_params=lambda r: llama.init_params(r, cfg),
        loss_fn=lambda p, b: llama.loss_fn(p, b, cfg),
        params_axes=llama.logical_axes(cfg),
        batch_axes={"tokens": ("batch", None)},
        optimizer=default_optimizer(1e-4, warmup_steps=10),
        scaling_config=ScalingConfig(
            mesh_spec=MeshSpec(dp=1, fsdp=len(devices)), devices=devices
        ),
        run_config=RunConfig(report_every=1_000_000),
    )


def _measure(cfg, devices, *, steps: int, warmup: int = 2) -> float:
    """Tokens/sec of the jitted train step (post-warmup)."""
    trainer = _make_trainer(cfg, devices)
    rng = np.random.default_rng(0)

    def batches():
        while True:
            yield {
                "tokens": rng.integers(
                    0, cfg.vocab_size, (BATCH, SEQ), dtype=np.int64
                ).astype(np.int32)
            }

    it = batches()
    with trainer.mesh:
        state = trainer.state
        step = trainer._step_fn
        # Pre-stage batches on device: host→device transfers ride a
        # potentially slow transport and real input pipelines overlap them
        # (ray_tpu.data prefetch), so they don't belong in the step timing.
        staged = [trainer.shard_batch(next(it)) for _ in range(min(steps, 4))]
        for _ in range(warmup):
            state, metrics = step(state, staged[0])
        # device_get, not block_until_ready: some PJRT transports (e.g. the
        # axon tunnel) return from block_until_ready before execution ends;
        # a host transfer of a value that depends on the whole step is the
        # only reliable fence.
        float(jax.device_get(metrics["loss"]))
        t0 = time.perf_counter()
        for i in range(steps):
            state, metrics = step(state, staged[i % len(staged)])
        float(jax.device_get(metrics["loss"]))
        dt = time.perf_counter() - t0
    return BATCH * SEQ * steps / dt


def main():
    devices = jax.devices()
    on_tpu = devices[0].platform != "cpu"
    steps = 10 if on_tpu else 2
    cfg = BENCH_CFG if on_tpu else dataclasses.replace(
        BENCH_CFG, dim=256, n_layers=4, n_heads=8, n_kv_heads=4, mlp_dim=1024
    )

    tps = _measure(cfg, devices, steps=steps)
    # Baseline: same step in float32 — the throughput of a port that
    # ignores the MXU's bf16 preference.  (f32 *without* remat, the truly
    # naive variant, OOMs outright at this size: 34 GB of attention probs.)
    baseline_cfg = dataclasses.replace(cfg, dtype=jax.numpy.float32,
                                       remat_policy="full")
    try:
        baseline_tps = _measure(baseline_cfg, devices, steps=max(2, steps // 3))
    except Exception:
        baseline_tps = float("nan")

    n_chips = len(devices)
    tps_chip = tps / n_chips
    from ray_tpu.parallel.mesh import detect_topology

    gen = detect_topology().generation
    flops_per_token = 6 * cfg.num_params()
    mfu = tps_chip * flops_per_token / PEAK_FLOPS.get(gen, 1e12)

    result = {
        "metric": f"llama_{cfg.num_params()/1e6:.0f}M_train_tokens_per_sec_per_chip",
        "value": round(tps_chip, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(tps / baseline_tps, 3) if baseline_tps == baseline_tps else None,
        "extra": {
            "chips": n_chips,
            "platform": gen,
            "mfu": round(mfu, 4),
            "batch": BATCH,
            "seq": SEQ,
            "params_m": round(cfg.num_params() / 1e6, 1),
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
