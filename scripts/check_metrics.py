#!/usr/bin/env python
"""Metric-name smoke check over the Prometheus text exposition.

Scrapes the live registry (or a saved exposition file) and fails when
any metric family violates the naming contract:

  * name grammar  — Prometheus metric names ``[a-zA-Z_:][a-zA-Z0-9_:]*``
    and label names ``[a-zA-Z_][a-zA-Z0-9_]*``;
  * repo grammar  — application families start with ``raytpu_`` and use
    lowercase snake_case (no uppercase, no dots, no dashes);
  * duplicates    — a family declared by more than one ``# TYPE`` line,
    or two live Metric instances registered under one name (a plane
    silently shadowing another plane's series);
  * histogram shape — ``histogram`` families expose exactly their
    ``_bucket``/``_sum``/``_count`` sample names;
  * label consistency — every sample of a family carries the same
    label-key set (``le`` and the federation-injected ``proc`` aside),
    so aggregation across a family can never silently group apart;
  * required families — callers may pass ``require=`` (CLI:
    ``--require a,b,c``) to fail when an expected family is absent —
    how CI pins the ``raytpu_serve_request_*`` plane.

Usage:
    python scripts/check_metrics.py            # scrape in-process
    python scripts/check_metrics.py FILE       # check a saved scrape
    python scripts/check_metrics.py --require raytpu_serve_ttft_seconds
Exit status 0 = clean, 1 = violations (listed on stderr).

The tier-1 telemetry test invokes ``check_exposition()`` directly, so
every CI run validates whatever metric set the suite just exercised.
"""

from __future__ import annotations

import re
import sys
from typing import Dict, List, Sequence

METRIC_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
LABEL_NAME_RE = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*$")
REPO_NAME_RE = re.compile(r"raytpu_[a-z0-9_]+$")
SAMPLE_LINE_RE = re.compile(
    r"([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})?\s+(\S+)$")
LABEL_PAIR_RE = re.compile(r'([^=,{]+)="((?:[^"\\]|\\.)*)"')


# Label keys excluded from the per-family consistency check: ``le``
# exists only on histogram _bucket samples (never _sum/_count), and
# ``proc`` is injected at export time onto federated worker copies of
# series the driver also emits bare.
CONSISTENCY_EXEMPT_LABELS = frozenset({"le", "proc"})


def check_exposition(text: str,
                     require: Sequence[str] = ()) -> List[str]:
    """Return a list of violations (empty = clean).  ``require`` names
    families that must be present in the exposition."""
    problems: List[str] = []
    families: Dict[str, str] = {}  # family -> type
    sample_names: Dict[str, set] = {}  # family -> sample suffix names
    seen_series: set = set()  # (sample name, sorted label pairs)
    label_sets: Dict[str, Dict[frozenset, int]] = {}  # fam -> keyset -> line

    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) < 4:
                problems.append(f"line {lineno}: malformed TYPE line")
                continue
            name, typ = parts[2], parts[3]
            if name in families:
                problems.append(
                    f"line {lineno}: duplicate family {name!r} "
                    f"(declared {families[name]!r}, redeclared {typ!r})")
            families[name] = typ
            if not METRIC_NAME_RE.match(name):
                problems.append(
                    f"line {lineno}: family {name!r} violates the "
                    f"Prometheus name grammar")
            elif not REPO_NAME_RE.match(name):
                problems.append(
                    f"line {lineno}: family {name!r} violates the repo "
                    f"grammar raytpu_<plane>_<what>[_<unit>]")
            continue
        if line.startswith("#"):
            continue
        m = SAMPLE_LINE_RE.match(line)
        if m is None:
            problems.append(f"line {lineno}: unparseable sample: {line!r}")
            continue
        sname, _, labels, _ = m.groups()
        if not METRIC_NAME_RE.match(sname):
            problems.append(
                f"line {lineno}: sample name {sname!r} violates the "
                f"Prometheus name grammar")
        # Suffix forms attach only to families DECLARED histogram —
        # matching on declared type (not name shape) means a counter
        # that happens to end in _count can never be mistaken for
        # another family's histogram sample.
        fam = next((f for f in families
                    if sname == f or (families[f] == "histogram"
                                      and sname.startswith(f + "_")
                                      and sname[len(f):] in
                                      ("_bucket", "_sum", "_count"))),
                   None)
        if fam is None:
            problems.append(
                f"line {lineno}: sample {sname!r} has no # TYPE "
                f"declaration")
        else:
            sample_names.setdefault(fam, set()).add(sname[len(fam):])
        pairs = LABEL_PAIR_RE.findall(labels or "")
        if fam is not None:
            keyset = frozenset(k for k, _v in pairs
                               if k not in CONSISTENCY_EXEMPT_LABELS)
            label_sets.setdefault(fam, {}).setdefault(keyset, lineno)
        for lname, _v in pairs:
            if not LABEL_NAME_RE.match(lname):
                problems.append(
                    f"line {lineno}: label {lname!r} violates the "
                    f"Prometheus label grammar")
        series = (sname, tuple(sorted(pairs)))
        if series in seen_series:
            problems.append(
                f"line {lineno}: duplicate series {sname}"
                f"{{{','.join(k + '=' + v for k, v in series[1])}}}")
        seen_series.add(series)

    for fam, typ in families.items():
        suffixes = sample_names.get(fam, set())
        if typ == "histogram":
            bad = suffixes - {"_bucket", "_sum", "_count"}
            if bad:
                problems.append(
                    f"family {fam!r}: histogram exposes unexpected "
                    f"sample suffixes {sorted(bad)}")
        elif suffixes - {""}:
            problems.append(
                f"family {fam!r}: {typ} exposes suffixed samples "
                f"{sorted(suffixes - {''})}")
    for fam, keysets in label_sets.items():
        if len(keysets) > 1:
            shapes = sorted("{" + ",".join(sorted(ks)) + "}"
                            for ks in keysets)
            problems.append(
                f"family {fam!r}: inconsistent label sets across "
                f"samples: {shapes} (first seen at lines "
                f"{sorted(keysets.values())})")
    for fam in require:
        if fam not in families:
            problems.append(
                f"required family {fam!r} absent from the exposition")
    return problems


def check_registry() -> List[str]:
    """In-process checks that the text format can't express."""
    from ray_tpu.util import metrics

    return [
        f"registry collision: two Metric instances registered as {n!r}"
        for n in metrics.registry().collisions()
    ]


def main(argv: List[str]) -> int:
    require: List[str] = []
    args = list(argv[1:])
    if "--require" in args:
        i = args.index("--require")
        require = [f for f in args[i + 1].split(",") if f]
        del args[i:i + 2]
    if args:
        text = open(args[0]).read()
        problems = check_exposition(text, require=require)
    else:
        from ray_tpu.util import metrics

        problems = check_exposition(metrics.export_prometheus(),
                                    require=require)
        problems += check_registry()
    for p in problems:
        print(f"check_metrics: {p}", file=sys.stderr)
    if problems:
        return 1
    print("check_metrics: exposition clean")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
