#!/usr/bin/env python
"""Schema check for the bench record (BENCH_OUT.json / bench stdout).

Hand-rolled validator (no jsonschema dependency) for the contract the
rest of the tooling leans on: scripts/gen_perf_tables.py renders the
README from these records, and the knee-honesty rules in bench.py are
only worth anything if a record violating them cannot land silently.

Checked:

  * top level    — metric (str), value (number), unit (str),
    extra (object) all present and typed;
  * serving blocks (extra.serving, extra.serving_1b,
    extra.llama_8b.serving_int8 — whichever exist and are not
    {"error": ...}):
      - required keys: ladder, knee_req_s, saturated, burst_req_per_s,
        burst_decode_tokens_per_s, prompt_len, gen, slots, kv,
        decode_kernel;
      - knee-vs-saturated EXCLUSIVITY: saturated is true iff
        knee_req_s is null (a collapsed ladder may never present a
        rung as its knee, and a ladder with a knee may not also claim
        saturation);
      - headline fields (arrival_rate_req_s, req_per_s,
        decode_tokens_per_s, ttft_p50_ms, ttft_p95_ms) are numbers at
        a knee and null when saturated;
      - each ladder rung has numeric offered_req_s / completion /
        ttft_p50_ms / ttft_p95_ms;
  * mixed-ladder blocks (extra.serving_mixed, extra.serving_1b_mixed —
    any extra.*serving*mixed* object that is not {"error": ...}):
      - batching is "ragged" or "interleaved", mixes is a non-empty
        object;
      - every mix is a full serving block (all the rules above,
        including knee/saturated exclusivity) AND carries its
        prompt_mix — the sampled prompt-length distribution (lens /
        weights / sampled_p50 / sampled_p95 / sampled_max) without
        which a per-mix knee TTFT is uninterpretable;
      - prompt_mix weights are non-negative and sum to 1 over lens of
        the same length;
  * prefix-cache blocks (a serving block's ``prefix``, reported by the
    zipf_chat mix): hit ratios in [0, 1], cold/hit50 request counts,
    and TTFT-by-hit-depth fields that are numeric or honestly null
    (null only when that depth class saw no requests); the optional
    ``prefix.migration`` field (migrated-vs-recomputed prefix cost)
    follows the same absent-not-zero rule — per-page costs null only
    when that side measured nothing;
  * speculative-decoding blocks (a serving leg's ``spec``, present
    only when the engine completed >= 1 verify round — absent, not
    zero): accept_ratio a fraction in [0, 1] (null only when nothing
    was drafted), accepted <= drafted, accepted_tokens_per_step > 0;
    the per-mix ``spec_ablation`` (burst spec-on/off A/B) exists iff
    the mix's spec leg ran, and never without a ``spec`` block;
  * dispatch-overhead blocks (a serving or disagg block's
    ``dispatch_overhead``, from serve/latency_attribution): component
    seconds non-negative, control_plane_share a fraction in [0, 1],
    requests >= 1 — a leg that attributed nothing omits the block
    (absent, not zero);
  * the disaggregation ablation (extra.serving_disagg): both legs
    carry TTFT + decode-ITL percentiles, and the disagg leg's
    migration block must show pages actually moved with bytes on the
    wire — a zero-page "disagg" leg measured unified serving twice;
  * the LoRA multiplexing ablation (extra.serving_adapters): Zipf
    adapter traffic vs the same prompts single-model — the multi leg
    carries its pool counters with hit_ratio a fraction in [0, 1],
    and throughput_degradation exists iff both legs actually ran;
  * the autoscaling chaos leg (extra.serving_chaos): goodput_ratio
    and shed_fraction are fractions in [0, 1], the run shows >= 1
    scale-up, >= 1 drain-based scale-down and >= 1 replica kill, and
    completed + shed <= offered; the scale_up_reasons breakdown (which
    signal fired each up decision) uses known reasons only, counts
    >= 1, absent-not-zero, summing to scale_ups; the control-plane
    chaos arm (controller_kills/recovery_seconds, present only in
    FT-era records) requires a measured recovery time whenever a
    controller was killed;
  * the full-8B train rung (extra.llama_8b.train): must be MEASURED
    (measured=true, numeric mfu/toks in (0, 1]/(0, inf)), carry
    zero_sharding=true + dp_shards, and satisfy the memory claim
    opt_state_bytes_per_param <= 2.5/dp_shards.  A lingering
    ``train_extrapolated`` key anywhere under llama_8b is a violation:
    that path is retired.

Usage:
    python scripts/bench_schema.py BENCH_OUT.json
Accepts a raw record or a driver wrapper ({"parsed": ..., "tail": ...}).
Exit status 0 = clean, 1 = violations (listed on stderr).

The tier-1 test (tests/test_bench_schema.py) calls validate_record()
directly on synthetic records and on BENCH_OUT.json when present.
"""

from __future__ import annotations

import json
import sys
from typing import Any, List

HEADLINE_KEYS = ("arrival_rate_req_s", "req_per_s",
                 "decode_tokens_per_s", "ttft_p50_ms", "ttft_p95_ms")
SERVING_REQUIRED = ("ladder", "knee_req_s", "saturated",
                    "burst_req_per_s", "burst_decode_tokens_per_s",
                    "prompt_len", "gen", "slots", "kv", "decode_kernel")
RUNG_REQUIRED = ("offered_req_s", "completion", "ttft_p50_ms",
                 "ttft_p95_ms")


def _num(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _check_prompt_mix(name: str, pm: Any, problems: List[str]) -> None:
    if not isinstance(pm, dict):
        problems.append(f"{name}: prompt_mix is not an object")
        return
    lens = pm.get("lens")
    weights = pm.get("weights")
    if (not isinstance(lens, list) or not lens
            or not all(_num(x) for x in lens)):
        problems.append(f"{name}: prompt_mix.lens must be a non-empty "
                        f"list of numbers, got {lens!r}")
    if (not isinstance(weights, list)
            or not all(_num(w) and w >= 0 for w in weights)):
        problems.append(f"{name}: prompt_mix.weights must be a list of "
                        f"non-negative numbers, got {weights!r}")
    elif isinstance(lens, list) and len(weights) != len(lens):
        problems.append(
            f"{name}: prompt_mix has {len(lens)} lens but "
            f"{len(weights)} weights")
    elif weights and abs(sum(weights) - 1.0) > 1e-3:
        problems.append(
            f"{name}: prompt_mix.weights sum to {sum(weights):.4f}, "
            f"not 1")
    for k in ("sampled_p50", "sampled_p95", "sampled_max"):
        if not _num(pm.get(k)):
            problems.append(
                f"{name}: prompt_mix.{k} missing or non-numeric: "
                f"{pm.get(k)!r}")


PREFIX_REQUIRED = ("requests", "hit_ratio", "hit_token_ratio",
                   "cold_requests", "hit50_requests", "cached_pages",
                   "evicted_pages")
PREFIX_TTFT_KEYS = ("ttft_mean_cold_ms", "ttft_mean_hit50_ms",
                    "ttft_p50_cold_ms", "ttft_p50_hit50_ms")


def _check_prefix(name: str, px: Any, problems: List[str]) -> None:
    """The prefix-cache block a zipf mix reports: hit ratios in [0, 1],
    TTFT-by-hit-depth numbers present (null only when that depth class
    had no requests — absent-not-zero, so a run with no cold requests
    can't fake an infinite speedup)."""
    if not isinstance(px, dict):
        problems.append(f"{name}: prefix is not an object")
        return
    for k in PREFIX_REQUIRED:
        if not _num(px.get(k)):
            problems.append(f"{name}: prefix.{k} missing or "
                            f"non-numeric: {px.get(k)!r}")
    for k in ("hit_ratio", "hit_token_ratio"):
        v = px.get(k)
        if _num(v) and not (0.0 <= v <= 1.0):
            problems.append(f"{name}: prefix.{k}={v!r} outside [0, 1]")
    for k in PREFIX_TTFT_KEYS:
        v = px.get(k)
        if v is not None and not _num(v):
            problems.append(f"{name}: prefix.{k}={v!r} is neither a "
                            f"number nor null")
    if (_num(px.get("cold_requests")) and px["cold_requests"] > 0
            and px.get("ttft_mean_cold_ms") is None):
        problems.append(f"{name}: prefix has cold_requests="
                        f"{px['cold_requests']} but null "
                        f"ttft_mean_cold_ms")
    if (_num(px.get("hit50_requests")) and px["hit50_requests"] > 0
            and px.get("ttft_mean_hit50_ms") is None):
        problems.append(f"{name}: prefix has hit50_requests="
                        f"{px['hit50_requests']} but null "
                        f"ttft_mean_hit50_ms")
    if "migration" in px:
        _check_prefix_migration(name, px["migration"], problems)


PREFIX_MIGRATION_REQUIRED = ("migrated_pages", "wire_bytes", "seconds")


def _check_prefix_migration(name: str, mg: Any,
                            problems: List[str]) -> None:
    """The migrated-vs-recomputed prefix-cost field (zipf_chat): this
    run's hot trie shipped to a cold engine over the int8 page wire,
    against the same run's measured cold-prefill cost.  Per-page costs
    may be null ONLY when their side measured nothing (no pages moved
    / no cold requests) — the same absent-not-zero rule as the TTFT
    depth classes, so a record can't fake an infinite migration win by
    dropping its baseline."""
    if not isinstance(mg, dict):
        problems.append(f"{name}: prefix.migration is not an object")
        return
    if "error" in mg:  # probe failed; the record says so — valid
        return
    for k in PREFIX_MIGRATION_REQUIRED:
        if not (_num(mg.get(k)) and mg[k] >= 0):
            problems.append(f"{name}: prefix.migration.{k} missing or "
                            f"not a number >= 0: {mg.get(k)!r}")
    for k in ("migrate_s_per_page", "recompute_s_per_page",
              "migrate_vs_recompute"):
        v = mg.get(k, None)
        if v is not None and not _num(v):
            problems.append(f"{name}: prefix.migration.{k}={v!r} is "
                            f"neither a number nor null")
    pages = mg.get("migrated_pages")
    if (_num(pages) and pages > 0
            and mg.get("migrate_s_per_page") is None):
        problems.append(f"{name}: prefix.migration has migrated_pages="
                        f"{pages} but null migrate_s_per_page")
    if (_num(pages) and pages > 0
            and not (_num(mg.get("wire_bytes"))
                     and mg["wire_bytes"] > 0)):
        problems.append(f"{name}: prefix.migration moved {pages} pages "
                        f"but put no bytes on the wire")


SPEC_REQUIRED = ("rounds", "drafted_tokens", "accepted_tokens",
                 "accept_ratio", "accepted_tokens_per_step", "k",
                 "draft")


def _check_spec(name: str, d: Any, problems: List[str]) -> None:
    """The speculative-decoding stats a serving leg may carry
    (bench.py reads them off LLMEngine.stats()['spec']).  A leg that
    never completed a verify round omits the block entirely — absent,
    not zero — so rounds must be >= 1 when the block exists.  The
    ratio is a fraction; accepted can never exceed drafted (each round
    accepts a prefix of what it drafted); accepted_tokens_per_step
    counts the bonus token so it is > 0 by construction."""
    if not isinstance(d, dict):
        problems.append(f"{name}: not an object")
        return
    for k in SPEC_REQUIRED:
        if k not in d:
            problems.append(f"{name}: missing required key {k!r}")
    rounds = d.get("rounds")
    if "rounds" in d and not (_num(rounds) and rounds >= 1):
        problems.append(
            f"{name}: rounds={rounds!r} — a leg that never speculated "
            f"must omit the spec block (absent, not zero)")
    for k in ("drafted_tokens", "accepted_tokens"):
        if k in d and not (_num(d[k]) and d[k] >= 0):
            problems.append(f"{name}: {k}={d.get(k)!r} must be a "
                            f"number >= 0")
    drafted = d.get("drafted_tokens")
    accepted = d.get("accepted_tokens")
    if _num(drafted) and _num(accepted) and accepted > drafted:
        problems.append(
            f"{name}: accepted_tokens={accepted} > drafted_tokens="
            f"{drafted} — a round accepts a prefix of its draft")
    ratio = d.get("accept_ratio", None)
    if ratio is None:
        if _num(drafted) and drafted > 0:
            problems.append(
                f"{name}: accept_ratio null with drafted_tokens="
                f"{drafted} — null is only honest when nothing was "
                f"drafted")
    elif not (_num(ratio) and 0.0 <= ratio <= 1.0):
        problems.append(f"{name}: accept_ratio={ratio!r} must be a "
                        f"fraction in [0, 1] or null")
    tps = d.get("accepted_tokens_per_step")
    if "accepted_tokens_per_step" in d and not (_num(tps) and tps > 0):
        problems.append(
            f"{name}: accepted_tokens_per_step={tps!r} must be > 0 "
            f"(every verify round emits at least the bonus token)")
    if "k" in d and not (_num(d["k"]) and d["k"] >= 1):
        problems.append(f"{name}: k={d.get('k')!r} must be a "
                        f"number >= 1")
    if "draft" in d and not isinstance(d.get("draft"), str):
        problems.append(f"{name}: draft={d.get('draft')!r} must name "
                        f"the draft model (e.g. 'self')")


def _check_spec_ablation(name: str, d: Any,
                         problems: List[str]) -> None:
    """The burst spec-on/off A/B a speculative mix leg carries: both
    legs measured the same prompts, so both must report a positive
    decode throughput, and only the ON leg may carry acceptance
    stats."""
    if not isinstance(d, dict):
        problems.append(f"{name}: not an object")
        return
    if "error" in d:  # probe failed; the record says so — valid
        return
    for leg in ("on", "off"):
        block = d.get(leg)
        if not isinstance(block, dict):
            problems.append(f"{name}.{leg}: missing or not an object")
            continue
        v = block.get("decode_tokens_per_s")
        if not (_num(v) and v > 0):
            problems.append(f"{name}.{leg}.decode_tokens_per_s="
                            f"{v!r} must be a number > 0")
        ar = block.get("accept_ratio", None)
        if leg == "off" and ar is not None:
            problems.append(
                f"{name}.off carries accept_ratio={ar!r} — the "
                f"spec-off leg has no acceptance to report")
        if leg == "on" and ar is not None \
                and not (_num(ar) and 0.0 <= ar <= 1.0):
            problems.append(f"{name}.on.accept_ratio={ar!r} must be "
                            f"a fraction in [0, 1] or null")
    speedup = d.get("speedup", None)
    if speedup is not None and not _num(speedup):
        problems.append(f"{name}: speedup={speedup!r} is neither a "
                        f"number nor null")


def _check_dispatch_overhead(name: str, do: Any,
                             problems: List[str]) -> None:
    """The per-request waterfall aggregate a serving leg may carry
    (serve/latency_attribution.aggregate): component seconds are
    non-negative numbers, control_plane_share is a fraction in [0, 1],
    and a leg that attributed nothing omits the block entirely —
    absent, not zero."""
    if not isinstance(do, dict):
        problems.append(f"{name}: not an object")
        return
    reqs = do.get("requests")
    if not (_num(reqs) and reqs >= 1):
        problems.append(
            f"{name}.requests missing or < 1: {reqs!r} — a leg that "
            f"attributed no requests must omit dispatch_overhead "
            f"(absent, not zero)")
    comps = do.get("components")
    if not isinstance(comps, dict) or not comps:
        problems.append(f"{name}.components missing or empty")
    else:
        for k, v in comps.items():
            if not (_num(v) and v >= 0):
                problems.append(
                    f"{name}.components.{k} not a number >= 0: {v!r}")
    share = do.get("control_plane_share")
    if not (_num(share) and 0.0 <= share <= 1.0):
        problems.append(
            f"{name}.control_plane_share not a fraction in [0, 1]: "
            f"{share!r}")
    e2e = do.get("e2e_mean_s")
    if not (_num(e2e) and e2e >= 0):
        problems.append(
            f"{name}.e2e_mean_s not a number >= 0: {e2e!r}")


def _check_serving(name: str, d: Any, problems: List[str]) -> None:
    if not isinstance(d, dict):
        problems.append(f"{name}: not an object")
        return
    if "error" in d:  # bench leg failed; the record says so — valid
        return
    for k in SERVING_REQUIRED:
        if k not in d:
            problems.append(f"{name}: missing required key {k!r}")
    knee = d.get("knee_req_s")
    saturated = d.get("saturated")
    if "saturated" in d and not isinstance(saturated, bool):
        problems.append(f"{name}: saturated must be a bool, got "
                        f"{type(saturated).__name__}")
    elif "saturated" in d and "knee_req_s" in d:
        if saturated and knee is not None:
            problems.append(
                f"{name}: saturated=true with knee_req_s={knee!r} — "
                f"a ladder is either saturated or has a knee, not both")
        if not saturated and knee is None:
            problems.append(
                f"{name}: saturated=false but knee_req_s is null — a "
                f"non-saturated ladder must name its knee")
        if saturated:
            for k in HEADLINE_KEYS:
                if d.get(k) is not None:
                    problems.append(
                        f"{name}: saturated record carries headline "
                        f"{k}={d[k]!r} (must be null: no rung "
                        f"sustained, so there is no honest headline)")
        else:
            for k in HEADLINE_KEYS:
                if k in d and not _num(d[k]):
                    problems.append(
                        f"{name}: headline {k}={d[k]!r} is not a "
                        f"number at a knee")
    ladder = d.get("ladder")
    if ladder is not None:
        if not isinstance(ladder, list) or not ladder:
            problems.append(f"{name}: ladder must be a non-empty list")
        else:
            for i, rung in enumerate(ladder):
                if not isinstance(rung, dict):
                    problems.append(f"{name}: ladder[{i}] not an object")
                    continue
                for k in RUNG_REQUIRED:
                    if not _num(rung.get(k)):
                        problems.append(
                            f"{name}: ladder[{i}].{k} missing or "
                            f"non-numeric: {rung.get(k)!r}")
    if "prompt_mix" in d:
        _check_prompt_mix(name, d["prompt_mix"], problems)
    if "prefix" in d:
        _check_prefix(name, d["prefix"], problems)
    if "spec" in d:
        _check_spec(f"{name}.spec", d["spec"], problems)
    if "spec_ablation" in d:
        _check_spec_ablation(f"{name}.spec_ablation",
                             d["spec_ablation"], problems)
        if "spec" not in d:
            problems.append(
                f"{name}: spec_ablation without a spec block — an "
                f"ablation over a leg that never speculated")
    if "dispatch_overhead" in d:
        _check_dispatch_overhead(f"{name}.dispatch_overhead",
                                 d["dispatch_overhead"], problems)


MULTIHOST_RUNG_REQUIRED = ("shards", "tp", "dcn_collective",
                           "toks_per_s", "ici_bytes_per_step",
                           "dcn_bytes_per_step",
                           "dcn_bytes_ratio_vs_fp32")


def _check_multihost(name: str, d: Any, problems: List[str]) -> None:
    """The multi-host serving ladder: shard-count rungs over a
    dcn_tp x tp mesh, each with its DCN-collective mode and the
    per-decode-step bytes-on-wire.  Every int8 rung must carry the
    quantization win — >= 3x under the fp32 accounting — or the record
    is claiming a multihost speedup it never measured."""
    if not isinstance(d, dict):
        problems.append(f"{name}: not an object")
        return
    if "error" in d:  # bench leg failed; the record says so — valid
        return
    ladder = d.get("ladder")
    if not isinstance(ladder, list) or not ladder:
        problems.append(f"{name}: ladder must be a non-empty list")
        return
    for i, rung in enumerate(ladder):
        sub = f"{name}.ladder[{i}]"
        if not isinstance(rung, dict):
            problems.append(f"{sub}: not an object")
            continue
        for k in MULTIHOST_RUNG_REQUIRED:
            if k not in rung:
                problems.append(f"{sub}: missing required key {k!r}")
        for k in ("shards", "tp"):
            if k in rung and not (_num(rung[k]) and rung[k] >= 1):
                problems.append(f"{sub}: {k}={rung.get(k)!r} must be a "
                                f"number >= 1")
        if ("dcn_collective" in rung
                and rung["dcn_collective"] not in ("int8", "bf16")):
            problems.append(
                f"{sub}: dcn_collective must be 'int8' or 'bf16', got "
                f"{rung.get('dcn_collective')!r}")
        if "toks_per_s" in rung and not (_num(rung["toks_per_s"])
                                         and rung["toks_per_s"] > 0):
            problems.append(f"{sub}: toks_per_s="
                            f"{rung.get('toks_per_s')!r} must be > 0")
        for k in ("ici_bytes_per_step", "dcn_bytes_per_step"):
            if k in rung and not (_num(rung[k]) and rung[k] >= 0):
                problems.append(f"{sub}: {k}={rung.get(k)!r} must be a "
                                f"number >= 0")
        shards = rung.get("shards")
        if _num(shards) and shards > 1 and not (
                _num(rung.get("dcn_bytes_per_step"))
                and rung["dcn_bytes_per_step"] > 0):
            problems.append(
                f"{sub}: shards={shards} but dcn_bytes_per_step="
                f"{rung.get('dcn_bytes_per_step')!r} — a multi-shard "
                f"rung puts bytes on the DCN")
        ratio = rung.get("dcn_bytes_ratio_vs_fp32")
        if ratio is not None and not _num(ratio):
            problems.append(f"{sub}: dcn_bytes_ratio_vs_fp32={ratio!r} "
                            f"is neither a number nor null")
        if (rung.get("dcn_collective") == "int8" and
                not (_num(ratio) and ratio >= 3.0)):
            problems.append(
                f"{sub}: int8 rung must show >= 3x DCN reduction, got "
                f"dcn_bytes_ratio_vs_fp32={ratio!r}")
    modes = {r.get("dcn_collective") for r in ladder
             if isinstance(r, dict)
             and _num(r.get("shards")) and r["shards"] > 1}
    if modes and not {"int8", "bf16"} <= modes:
        problems.append(
            f"{name}: multi-shard rungs must run the int8-vs-bf16 "
            f"ablation, found only {sorted(modes)}")


DISAGG_LEG_REQUIRED = ("ttft_p50_ms", "ttft_p95_ms", "itl_p50_ms",
                       "itl_p95_ms", "decode_tokens_per_s")
DISAGG_MIG_REQUIRED = ("pages", "wire_bytes", "seconds", "failed")


def _check_disagg(name: str, d: Any, problems: List[str]) -> None:
    """The long_rag disaggregation on/off ablation: one unified engine
    vs prefill -> kv_transfer page migration -> decode.  Both legs must
    carry TTFT and decode-ITL percentiles (the two latencies the
    role split exists to separate), and the disagg leg must have
    actually moved pages over the wire — a 'disagg' record whose
    migration block shows zero pages measured unified serving twice."""
    if not isinstance(d, dict):
        problems.append(f"{name}: not an object")
        return
    if "error" in d:  # bench leg failed; the record says so — valid
        return
    for k in ("mix", "unified", "disagg", "n_requests", "gen",
              "handoff_after_tokens", "transfer"):
        if k not in d:
            problems.append(f"{name}: missing required key {k!r}")
    if "transfer" in d and d["transfer"] not in ("int8", "exact"):
        problems.append(f"{name}: transfer must be 'int8' or 'exact', "
                        f"got {d.get('transfer')!r}")
    mix = d.get("mix")
    if mix is not None:
        if not isinstance(mix, dict):
            problems.append(f"{name}: mix is not an object")
        else:
            if not isinstance(mix.get("name"), str):
                problems.append(f"{name}: mix.name missing or "
                                f"non-string: {mix.get('name')!r}")
            lens = mix.get("lens")
            weights = mix.get("weights")
            if (not isinstance(lens, list) or not lens
                    or not all(_num(x) for x in lens)):
                problems.append(f"{name}: mix.lens must be a non-empty "
                                f"list of numbers, got {lens!r}")
            if (not isinstance(weights, list)
                    or not all(_num(w) and w >= 0 for w in weights)):
                problems.append(f"{name}: mix.weights must be a list "
                                f"of non-negative numbers, got "
                                f"{weights!r}")
            elif isinstance(lens, list) and len(weights) != len(lens):
                problems.append(f"{name}: mix has {len(lens)} lens but "
                                f"{len(weights)} weights")
            elif weights and abs(sum(weights) - 1.0) > 1e-3:
                problems.append(f"{name}: mix.weights sum to "
                                f"{sum(weights):.4f}, not 1")
    for leg in ("unified", "disagg"):
        block = d.get(leg)
        if block is None:
            continue
        if not isinstance(block, dict):
            problems.append(f"{name}.{leg}: not an object")
            continue
        for k in DISAGG_LEG_REQUIRED:
            if not _num(block.get(k)):
                problems.append(f"{name}.{leg}.{k} missing or "
                                f"non-numeric: {block.get(k)!r}")
    dis = d.get("disagg")
    if isinstance(dis, dict):
        mg = dis.get("migration")
        if not isinstance(mg, dict):
            problems.append(f"{name}.disagg: missing migration block")
        else:
            for k in DISAGG_MIG_REQUIRED:
                if not (_num(mg.get(k)) and mg[k] >= 0):
                    problems.append(
                        f"{name}.disagg.migration.{k} missing or not a "
                        f"number >= 0: {mg.get(k)!r}")
            if _num(mg.get("pages")) and mg["pages"] == 0:
                problems.append(
                    f"{name}.disagg.migration.pages=0 — a disagg leg "
                    f"that never moved a page measured unified serving "
                    f"twice")
            if (_num(mg.get("pages")) and mg["pages"] > 0
                    and not (_num(mg.get("wire_bytes"))
                             and mg["wire_bytes"] > 0)):
                problems.append(
                    f"{name}.disagg.migration: pages={mg['pages']} put "
                    f"no bytes on the wire")
    ratio = d.get("itl_p95_ratio", None)
    if ratio is not None and not _num(ratio):
        problems.append(f"{name}: itl_p95_ratio={ratio!r} is neither "
                        f"a number nor null")
    if "dispatch_overhead" in d:
        _check_dispatch_overhead(f"{name}.dispatch_overhead",
                                 d["dispatch_overhead"], problems)


ADAPTER_LEG_REQUIRED = ("tokens_per_s", "ttft_p50_ms", "ttft_p95_ms")


def _check_adapters(name: str, d: Any, problems: List[str]) -> None:
    """The zipf_adapters multiplexing ablation: Zipf adapter traffic
    through the paged LoRA pool vs the same prompts single-model.
    The multi leg must carry the pool counters (hit_ratio a fraction),
    and the record must price the multiplexing — a degradation ratio
    exists iff both legs actually ran."""
    if not isinstance(d, dict):
        problems.append(f"{name}: not an object")
        return
    if "error" in d:  # bench leg failed; the record says so — valid
        return
    for k in ("mix", "n_requests", "gen", "single_model", "multi",
              "throughput_degradation"):
        if k not in d:
            problems.append(f"{name}: missing required key {k!r}")
    mix = d.get("mix")
    if mix is not None:
        if not isinstance(mix, dict):
            problems.append(f"{name}: mix is not an object")
        else:
            if mix.get("name") != "zipf_adapters":
                problems.append(f"{name}: mix.name must be "
                                f"'zipf_adapters', got "
                                f"{mix.get('name')!r}")
            for k in ("n_adapters", "zipf_alpha", "pool_adapters"):
                if not (_num(mix.get(k)) and mix[k] > 0):
                    problems.append(f"{name}: mix.{k}={mix.get(k)!r} "
                                    f"must be a number > 0")
    ran = True
    for leg in ("single_model", "multi"):
        block = d.get(leg)
        if block is None:
            ran = False
            continue
        if not isinstance(block, dict):
            problems.append(f"{name}.{leg}: not an object")
            ran = False
            continue
        for k in ADAPTER_LEG_REQUIRED:
            if not (_num(block.get(k)) and block[k] > 0):
                problems.append(f"{name}.{leg}.{k} missing or not a "
                                f"number > 0: {block.get(k)!r}")
    multi = d.get("multi")
    if isinstance(multi, dict):
        pool = multi.get("pool")
        if not isinstance(pool, dict):
            problems.append(f"{name}.multi: missing pool block — a "
                            f"multiplexed leg without its pool "
                            f"counters measured nothing multi-tenant")
        else:
            for k in ("pool_pages", "resident", "hits", "misses",
                      "evictions"):
                if not (_num(pool.get(k)) and pool[k] >= 0):
                    problems.append(
                        f"{name}.multi.pool.{k} missing or not a "
                        f"number >= 0: {pool.get(k)!r}")
            hr = pool.get("hit_ratio")
            if not (_num(hr) and 0.0 <= hr <= 1.0):
                problems.append(
                    f"{name}.multi.pool.hit_ratio={hr!r} must be a "
                    f"fraction in [0, 1]")
    degr = d.get("throughput_degradation", None)
    if ran and not (_num(degr) and degr > 0):
        problems.append(
            f"{name}: throughput_degradation={degr!r} — both legs ran "
            f"but the record never priced the multiplexing")
    if not ran and degr is not None:
        problems.append(
            f"{name}: throughput_degradation={degr!r} without both "
            f"legs — a ratio over a leg that never ran")


ZERO_TRAIN_REQUIRED = ("params_b", "measured", "tokens_per_sec_per_chip",
                       "mfu", "zero_sharding", "dp_shards", "grad_accum",
                       "optimizer", "opt_state_bytes_per_param")


def _check_zero(name: str, d: Any, problems: List[str]) -> None:
    """The full-8B train rung: MEASURED end-to-end with ZeRO-sharded
    optimizer state, never extrapolated from a layer subset.  The
    memory claim is load-bearing — int8 Adam states cost ~2 B/param,
    so a rung sharded ``dp_shards`` ways must report
    opt_state_bytes_per_param <= 2.5/dp_shards or it never actually
    sharded the state it says it did."""
    if not isinstance(d, dict):
        problems.append(f"{name}: not an object")
        return
    if "error" in d:  # bench leg infeasible/failed; the record says so
        return
    for k in ZERO_TRAIN_REQUIRED:
        if k not in d:
            problems.append(f"{name}: missing required key {k!r}")
    if "measured" in d and d["measured"] is not True:
        problems.append(
            f"{name}: measured={d['measured']!r} — the extrapolated 8B "
            f"train path is retired; only measured rungs may land")
    if "zero_sharding" in d and d["zero_sharding"] is not True:
        problems.append(
            f"{name}: zero_sharding={d['zero_sharding']!r} — the full-8B "
            f"rung only fits with the optimizer state sharded")
    shards = d.get("dp_shards")
    if "dp_shards" in d and not (_num(shards) and shards >= 1):
        problems.append(f"{name}: dp_shards={shards!r} must be a "
                        f"number >= 1")
    for k in ("tokens_per_sec_per_chip", "mfu"):
        if k in d and not (_num(d[k]) and d[k] > 0):
            problems.append(f"{name}: {k}={d.get(k)!r} must be a "
                            f"number > 0")
    mfu = d.get("mfu")
    if _num(mfu) and mfu > 1.0:
        problems.append(f"{name}: mfu={mfu!r} > 1 — not a fraction of "
                        f"peak; this is not a measurement")
    bpp = d.get("opt_state_bytes_per_param")
    if "opt_state_bytes_per_param" in d and not (_num(bpp) and bpp > 0):
        problems.append(f"{name}: opt_state_bytes_per_param={bpp!r} "
                        f"must be a number > 0")
    elif _num(bpp) and _num(shards) and shards >= 1 \
            and bpp > 2.5 / shards + 1e-9:
        problems.append(
            f"{name}: opt_state_bytes_per_param={bpp:.4f} exceeds "
            f"2.5/dp_shards={2.5 / shards:.4f} — int8 Adam states "
            f"sharded {int(shards)} ways cost ~2/dp_shards B/param; "
            f"this rung kept replicated state")


CHAOS_REQUIRED = ("mix", "offered", "completed", "shed",
                  "shed_fraction", "goodput_ratio", "scale_ups",
                  "scale_downs", "kills")


# Every scale-up decision carries exactly one reason tag: predictive
# arrival_slope, reactive queue_age/goodput pressure, or the plain
# averaged-ongoing policy.
AUTOSCALE_REASONS = ("arrival_slope", "queue_age", "goodput", "ongoing")


def _check_autoscale_signals(name: str, d: Any,
                             problems: List[str]) -> None:
    """The chaos leg's scale-up reason breakdown (scale_up_reasons):
    which autoscaling signal fired each up decision.  Absent-not-zero:
    a reason that never fired must be omitted, not reported as 0 — so
    readers can tell "predictive arm never ran" (key absent in an old
    record) from "ran and decided nothing" (key absent in a new one)
    without a sentinel.  Keys come from AUTOSCALE_REASONS, values are
    counts >= 1, and the breakdown must sum to scale_ups when both are
    present (every up decision has exactly one reason)."""
    if not isinstance(d, dict):
        problems.append(f"{name}: not an object")
        return
    for reason, n in d.items():
        if reason not in AUTOSCALE_REASONS:
            problems.append(
                f"{name}: unknown reason {reason!r} (known: "
                f"{', '.join(AUTOSCALE_REASONS)})")
        if not (isinstance(n, int) and not isinstance(n, bool)
                and n >= 1):
            problems.append(
                f"{name}: {reason}={n!r} must be an int >= 1 — "
                f"reasons that never fired are omitted, not zero")


def _check_doctor(name: str, d: Any, problems: List[str]) -> None:
    """The chaos leg's post-ramp invariant audit (doctor): a deep
    cross-plane consistency pass over every surviving engine after the
    kills, replays and drain-downs settle.  checks_run must be >= 1 (an
    audit that ran zero checks audited nothing) and violations must be
    exactly 0 — a nonzero count means the chaos leg corrupted engine
    state and the record is a failure regardless of goodput."""
    if not isinstance(d, dict):
        problems.append(f"{name}: not an object")
        return
    n = d.get("checks_run")
    if not (isinstance(n, int) and not isinstance(n, bool) and n >= 1):
        problems.append(f"{name}: checks_run={n!r} must be an int >= 1 "
                        f"— a doctor pass that ran no checks audited "
                        f"nothing")
    v = d.get("violations")
    if not (isinstance(v, int) and not isinstance(v, bool) and v == 0):
        problems.append(f"{name}: violations={v!r} must be exactly 0 — "
                        f"the chaos leg left corrupted engine state")
    s = d.get("audit_seconds")
    if not (_num(s) and s >= 0):
        problems.append(f"{name}: audit_seconds={s!r} must be a "
                        f"number >= 0")


def _check_chaos(name: str, d: Any, problems: List[str]) -> None:
    """The autoscaling chaos leg (extra.serving_chaos): ramped+bursty
    zipf_chat arrival against an autoscaled deployment with the
    replica killer active.  The record must show the policy actually
    exercised — at least one scale-up, at least one drain-based
    scale-down, and at least one replica killed — or the 'chaos' leg
    measured a static fleet on a sunny day.  Goodput and shed fraction
    are fractions in [0, 1]; sheds are not goodput failures (nothing
    ran), so completed + shed <= offered."""
    if not isinstance(d, dict):
        problems.append(f"{name}: not an object")
        return
    if "error" in d:  # bench leg failed; the record says so — valid
        return
    for k in CHAOS_REQUIRED:
        if k not in d:
            problems.append(f"{name}: missing required key {k!r}")
    for k in ("offered", "completed", "shed", "kills",
              "scale_ups", "scale_downs"):
        if k in d and not (_num(d[k]) and d[k] >= 0):
            problems.append(f"{name}: {k}={d.get(k)!r} must be a "
                            f"number >= 0")
    for k in ("goodput_ratio", "shed_fraction"):
        v = d.get(k)
        if k in d and not (_num(v) and 0.0 <= v <= 1.0):
            problems.append(f"{name}: {k}={v!r} must be a fraction "
                            f"in [0, 1]")
    if _num(d.get("scale_ups")) and d["scale_ups"] < 1:
        problems.append(
            f"{name}: scale_ups={d['scale_ups']!r} — a chaos leg whose "
            f"load never forced a scale-up tested a static fleet")
    if _num(d.get("scale_downs")) and d["scale_downs"] < 1:
        problems.append(
            f"{name}: scale_downs={d['scale_downs']!r} — the ramp-down "
            f"must drive at least one drain-based scale-down or the "
            f"drain path went unexercised")
    if _num(d.get("kills")) and d["kills"] < 1:
        problems.append(
            f"{name}: kills={d['kills']!r} — a chaos leg with no "
            f"replica killed measured ordinary serving")
    if (_num(d.get("offered")) and _num(d.get("completed"))
            and _num(d.get("shed"))
            and d["completed"] + d["shed"] > d["offered"] + 1e-9):
        problems.append(
            f"{name}: completed={d['completed']} + shed={d['shed']} "
            f"exceeds offered={d['offered']}")
    # Control-plane chaos arm (absent in pre-FT records — validated
    # only when present, so old BENCH_OUT.json files stay clean):
    # controller_kills counts mid-ramp controller SIGKILLs, and every
    # kill must come with a measured recovery — a record claiming a
    # controller kill without a recovery time either never recovered
    # (a failure) or never timed it (not a measurement).
    ck = d.get("controller_kills", None)
    if "controller_kills" in d and not (_num(ck) and ck >= 0):
        problems.append(f"{name}: controller_kills={ck!r} must be a "
                        f"number >= 0")
    rs = d.get("recovery_seconds", None)
    if _num(ck) and ck >= 1:
        if not (_num(rs) and rs >= 0):
            problems.append(
                f"{name}: controller_kills={ck} but recovery_seconds="
                f"{rs!r} — a killed controller must be observed "
                f"recovering (new actor answering status) with a "
                f"measured recovery time")
    elif rs is not None and not _num(rs):
        problems.append(f"{name}: recovery_seconds={rs!r} is neither "
                        f"a number nor null")
    if "scale_up_reasons" in d:
        sub = d["scale_up_reasons"]
        _check_autoscale_signals(f"{name}.scale_up_reasons", sub,
                                 problems)
        if (isinstance(sub, dict) and _num(d.get("scale_ups"))
                and all(isinstance(v, int) for v in sub.values())
                and sum(sub.values()) != d["scale_ups"]):
            problems.append(
                f"{name}.scale_up_reasons: breakdown sums to "
                f"{sum(sub.values())} but scale_ups={d['scale_ups']} — "
                f"every up decision carries exactly one reason")
    if "doctor" in d:
        _check_doctor(f"{name}.doctor", d["doctor"], problems)


def _check_mixed(name: str, d: Any, problems: List[str]) -> None:
    """A mixed-length ladder block: one serving record per prompt mix,
    each carrying the distribution that produced its knee."""
    if not isinstance(d, dict):
        problems.append(f"{name}: not an object")
        return
    if "error" in d:
        return
    if d.get("batching") not in ("ragged", "interleaved"):
        problems.append(
            f"{name}: batching must be 'ragged' or 'interleaved', got "
            f"{d.get('batching')!r}")
    mixes = d.get("mixes")
    if not isinstance(mixes, dict) or not mixes:
        problems.append(f"{name}: mixes must be a non-empty object")
        return
    for mix, block in mixes.items():
        sub = f"{name}.mixes[{mix}]"
        _check_serving(sub, block, problems)
        if (isinstance(block, dict) and "error" not in block
                and "prompt_mix" not in block):
            problems.append(
                f"{sub}: missing prompt_mix — a per-mix knee TTFT "
                f"without its prompt-length distribution is "
                f"uninterpretable")
        # Ablation iff spec ran: a mix leg that speculated must price
        # the machinery (spec-on/off A/B), and a leg that never
        # speculated cannot carry one.
        if (isinstance(block, dict) and "error" not in block
                and "spec" in block and "spec_ablation" not in block):
            problems.append(
                f"{sub}: spec block without spec_ablation — a "
                f"speculative mix leg must carry its on/off A/B")


def validate_record(rec: Any) -> List[str]:
    """Return a list of violations (empty = clean)."""
    problems: List[str] = []
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, expected an object"]
    if not isinstance(rec.get("metric"), str):
        problems.append("missing/non-string top-level key 'metric'")
    if not _num(rec.get("value")):
        problems.append("missing/non-numeric top-level key 'value'")
    if not isinstance(rec.get("unit"), str):
        problems.append("missing/non-string top-level key 'unit'")
    extra = rec.get("extra")
    if not isinstance(extra, dict):
        problems.append("missing/non-object top-level key 'extra'")
        return problems
    for name, block in (("extra.serving", extra.get("serving")),
                        ("extra.serving_1b", extra.get("serving_1b"))):
        if block is not None:
            _check_serving(name, block, problems)
    b8 = extra.get("llama_8b")
    if isinstance(b8, dict) and b8.get("serving_int8") is not None:
        _check_serving("extra.llama_8b.serving_int8",
                       b8["serving_int8"], problems)
    if isinstance(b8, dict):
        if "train_extrapolated" in b8:
            problems.append(
                "extra.llama_8b.train_extrapolated: the extrapolated "
                "8B train path is retired — re-run bench.py for the "
                "measured ZeRO-sharded 'train' rung")
        if "error" not in b8:
            if "train" not in b8:
                problems.append(
                    "extra.llama_8b: missing the measured 'train' rung "
                    "(full-8B AdamW, ZeRO-sharded)")
            else:
                _check_zero("extra.llama_8b.train", b8["train"],
                            problems)
    for key, block in extra.items():
        if "serving" in key and "mixed" in key and block is not None:
            _check_mixed(f"extra.{key}", block, problems)
    if extra.get("serving_multihost") is not None:
        _check_multihost("extra.serving_multihost",
                         extra["serving_multihost"], problems)
    if extra.get("serving_disagg") is not None:
        _check_disagg("extra.serving_disagg", extra["serving_disagg"],
                      problems)
    if extra.get("serving_adapters") is not None:
        _check_adapters("extra.serving_adapters",
                        extra["serving_adapters"], problems)
    if extra.get("serving_chaos") is not None:
        _check_chaos("extra.serving_chaos", extra["serving_chaos"],
                     problems)
    return problems


def main(argv: List[str]) -> int:
    path = argv[1] if len(argv) > 1 else "BENCH_OUT.json"
    with open(path) as f:
        rec = json.load(f)
    if isinstance(rec, dict) and "parsed" in rec:
        if rec["parsed"] is None:
            print(f"bench_schema: {path}: driver wrapper with "
                  f"parsed=null — validate the BENCH_OUT.json the run "
                  f"wrote instead", file=sys.stderr)
            return 1
        rec = rec["parsed"]
    problems = validate_record(rec)
    for p in problems:
        print(f"bench_schema: {p}", file=sys.stderr)
    if problems:
        return 1
    print(f"bench_schema: {path} clean")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
