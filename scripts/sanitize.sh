#!/usr/bin/env bash
# Sanitizer CI for the native layer (SURVEY §5.2 parity: the reference
# runs its C++ under TSAN/ASAN bazel configs; this is our equivalent).
#
#   scripts/sanitize.sh [iters]
#
# Builds the shm object store and cluster scheduler together with their
# stress drivers under -fsanitize=thread and -fsanitize=address,undefined
# and runs them.  Any data race / heap error / invariant violation makes
# the script exit nonzero.  Invoked by tests/test_sanitizers.py.
set -u
ITERS="${1:-1500}"
HERE="$(cd "$(dirname "$0")/.." && pwd)"
SRC="$HERE/ray_tpu/_native"
OUT="$(mktemp -d /tmp/raytpu_sanitize.XXXXXX)"
trap 'rm -rf "$OUT"' EXIT

CXX="${CXX:-g++}"
COMMON="-std=c++17 -g -O1 -fno-omit-frame-pointer -pthread"
FAIL=0

build_run() {
  local tag="$1" flags="$2" driver="$3" lib="$4"; shift 4
  local bin="$OUT/${driver%.cc}_$tag"
  if ! "$CXX" $COMMON $flags -o "$bin" "$SRC/$driver" "$SRC/$lib" -lrt 2>"$OUT/build_$tag.log"; then
    echo "BUILD FAIL [$tag $driver]"; cat "$OUT/build_$tag.log"; FAIL=1; return
  fi
  if ! "$bin" "$@" >"$OUT/run_${driver%.cc}_$tag.log" 2>&1; then
    echo "SANITIZE FAIL [$tag $driver]"
    tail -40 "$OUT/run_${driver%.cc}_$tag.log"
    FAIL=1
  else
    echo "ok [$tag $driver] $(tail -1 "$OUT/run_${driver%.cc}_$tag.log")"
  fi
}

TSAN="-fsanitize=thread"
ASAN="-fsanitize=address,undefined -fno-sanitize-recover=all"

export TSAN_OPTIONS="halt_on_error=1 second_deadlock_stack=1"
export ASAN_OPTIONS="detect_leaks=1 abort_on_error=0"
export UBSAN_OPTIONS="halt_on_error=1 print_stacktrace=1"

build_run tsan "$TSAN" stress_sched.cc scheduler.cc "$ITERS"
build_run asan "$ASAN" stress_sched.cc scheduler.cc "$ITERS"
# shm store: threads + forked processes over one mapped segment.  TSAN
# cannot follow the forked children (it sees the parent's threads only);
# run it single-process multi-thread there and full multi-process under
# ASAN.
build_run tsan "$TSAN" stress_shm.cc shm_store.cc "$ITERS" 0
build_run asan "$ASAN" stress_shm.cc shm_store.cc "$ITERS" 2

exit $FAIL
